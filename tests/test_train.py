"""Training-layer tests: metrics, optimizer parity, steps, checkpoints, loop."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import ExperimentConfig, preset
from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
from stmgcn_tpu.experiment import build_dataset, build_supports, build_trainer
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.train import (
    MAPE,
    MSE,
    PCC,
    RMSE,
    Trainer,
    load_checkpoint,
    make_optimizer,
    make_step_fns,
    save_checkpoint,
    regression_report,
)


class TestMetrics:
    def test_known_values(self):
        pred = np.array([1.0, 2.0, 3.0])
        true = np.array([1.0, 3.0, 5.0])
        assert MSE(pred, true) == pytest.approx(5.0 / 3.0)
        assert RMSE(pred, true) == pytest.approx(np.sqrt(5.0 / 3.0))

    def test_mape_epsilon_guard(self):
        # reference: |err| / (y + 1.0) (Model_Trainer.py:110)
        pred = np.array([1.0])
        true = np.array([0.0])
        assert MAPE(pred, true) == pytest.approx(1.0)

    def test_pcc_perfect(self):
        x = np.arange(10.0)
        assert PCC(2 * x + 1, x) == pytest.approx(1.0)

    def test_report_keys(self):
        r = regression_report(np.ones(4), np.ones(4) * 2)
        assert set(r) == {"mse", "rmse", "mae", "mape", "pcc"}


class TestOptimizerParity:
    def test_matches_torch_adam_with_l2(self):
        """optax chain == torch.optim.Adam(lr, weight_decay=wd) over 5 steps."""
        torch = pytest.importorskip("torch")
        rng = np.random.default_rng(0)
        w0 = rng.standard_normal((4, 3)).astype(np.float32)
        grads = [rng.standard_normal((4, 3)).astype(np.float32) for _ in range(5)]
        lr, wd = 2e-3, 1e-4

        p = torch.nn.Parameter(torch.tensor(w0.copy()))
        opt = torch.optim.Adam([p], lr=lr, weight_decay=wd)
        for g in grads:
            opt.zero_grad()
            p.grad = torch.tensor(g)
            opt.step()
        want = p.detach().numpy()

        tx = make_optimizer(lr, wd)
        params = {"w": jnp.asarray(w0)}
        state = tx.init(params)
        for g in grads:
            updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
        np.testing.assert_allclose(np.asarray(params["w"]), want, rtol=1e-5, atol=1e-6)

    def test_cosine_schedule_warms_up_and_decays(self):
        """Update magnitude follows warmup -> peak -> cosine floor; the
        constant-gradient updates isolate the schedule from Adam."""
        tx = make_optimizer(
            1e-2, 0.0, schedule="cosine", warmup_steps=5, decay_steps=50,
            min_lr_fraction=0.1,
        )
        params = {"w": jnp.zeros(3)}
        state = tx.init(params)
        g = {"w": jnp.ones(3)}
        mags = []
        for _ in range(50):
            updates, state = tx.update(g, state, params)
            mags.append(float(jnp.abs(updates["w"]).max()))
        assert mags[0] < mags[4] < mags[5]          # linear warmup
        assert mags[5] == max(mags)                 # peak right after warmup
        assert mags[-1] < mags[5] * 0.2             # decayed near the floor
        assert mags[-1] > 0                          # not to zero (floor 0.1)

    def test_cosine_needs_decay_steps(self):
        with pytest.raises(ValueError, match="decay_steps"):
            make_optimizer(1e-2, schedule="cosine")
        with pytest.raises(ValueError, match="schedule"):
            make_optimizer(1e-2, schedule="linear")

    def test_grad_clip_bounds_raw_gradient(self):
        """Clipping applies to the RAW gradient (before L2/Adam): a huge
        gradient produces the same update as its rescaled-to-norm copy."""
        tx = make_optimizer(1e-2, 1e-4, grad_clip_norm=1.0)
        params = {"w": jnp.ones(4)}
        big = {"w": jnp.full(4, 100.0)}
        small = {"w": jnp.full(4, 100.0) / jnp.linalg.norm(jnp.full(4, 100.0))}
        s1 = tx.init(params)
        u_big, _ = tx.update(big, s1, params)
        s2 = tx.init(params)
        u_small, _ = tx.update(small, s2, params)
        np.testing.assert_allclose(
            np.asarray(u_big["w"]), np.asarray(u_small["w"]), rtol=1e-6
        )
        with pytest.raises(ValueError, match="grad_clip_norm"):
            make_optimizer(1e-2, grad_clip_norm=0.0)

    def test_schedule_misconfigurations_raise(self):
        # warmup/floor with schedule='none' would be silently ignored
        with pytest.raises(ValueError, match="cosine"):
            make_optimizer(1e-2, warmup_steps=5)
        with pytest.raises(ValueError, match="cosine"):
            make_optimizer(1e-2, min_lr_fraction=0.1)
        # warmup at least as long as the run never decays
        with pytest.raises(ValueError, match="warmup_steps"):
            make_optimizer(1e-2, schedule="cosine", warmup_steps=50, decay_steps=50)
        # a negative floor would cross zero into gradient ascent
        with pytest.raises(ValueError, match="min_lr_fraction"):
            make_optimizer(
                1e-2, schedule="cosine", decay_steps=50, min_lr_fraction=-0.1
            )


def tiny_setup(seed=0, M=2, N=9, T=5, B=8):
    rng = np.random.default_rng(seed)
    sup = jnp.asarray(rng.standard_normal((M, 3, N, N)).astype(np.float32) * 0.2)
    model = STMGCN(m_graphs=M, n_supports=3, seq_len=T, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
    x = jnp.asarray(rng.standard_normal((B, T, N, 1)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, N, 1)).astype(np.float32) * 0.1)
    return model, sup, x, y


class TestStepFns:
    def test_masked_loss_equals_ragged(self):
        model, sup, x, y = tiny_setup()
        fns = make_step_fns(model, make_optimizer(1e-3), "mse")
        params, _ = fns.init(jax.random.key(0), sup, x)
        # full batch of 8, but only 5 real samples
        mask = jnp.asarray((np.arange(8) < 5).astype(np.float32))
        loss_masked, _ = fns.eval_step(params, sup, x, y, mask)
        loss_ragged, _ = fns.eval_step(params, sup, x[:5], y[:5], jnp.ones(5))
        np.testing.assert_allclose(float(loss_masked), float(loss_ragged), rtol=1e-6)

    def test_training_reduces_loss(self):
        model, sup, x, y = tiny_setup()
        fns = make_step_fns(model, make_optimizer(1e-2), "mse")
        params, opt_state = fns.init(jax.random.key(0), sup, x)
        mask = jnp.ones(x.shape[0])
        first = None
        for i in range(30):
            params, opt_state, loss = fns.train_step(params, opt_state, sup, x, y, mask)
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.5

    @pytest.mark.parametrize("loss", ["mse", "mae", "huber"])
    def test_loss_kinds(self, loss):
        model, sup, x, y = tiny_setup()
        fns = make_step_fns(model, make_optimizer(1e-3), loss)
        params, opt_state = fns.init(jax.random.key(0), sup, x)
        _, _, val = fns.train_step(params, opt_state, sup, x, y, jnp.ones(x.shape[0]))
        assert np.isfinite(float(val))

    def test_unknown_loss_raises(self):
        model, *_ = tiny_setup()
        with pytest.raises(ValueError, match="loss"):
            make_step_fns(model, make_optimizer(1e-3), "nll")


class TestCheckpoint:
    def test_roundtrip_with_templates(self, tmp_path):
        model, sup, x, y = tiny_setup()
        fns = make_step_fns(model, make_optimizer(1e-3, 1e-4), "mse")
        params, opt_state = fns.init(jax.random.key(0), sup, x)
        params2, opt_state2, _ = fns.train_step(params, opt_state, sup, x, y,
                                                jnp.ones(x.shape[0]))
        path = str(tmp_path / "t.ckpt")
        meta = {"epoch": 3, "best_val": 0.5, "normalizer": {"kind": "minmax",
                "minimum": 0.0, "maximum": 9.0}}
        save_checkpoint(path, params2, opt_state2, meta)
        meta_l, params_l, opt_l = load_checkpoint(path, params, opt_state)
        assert meta_l == meta
        jax.tree.map(np.testing.assert_array_equal, params_l, params2)
        jax.tree.map(
            np.testing.assert_array_equal,
            jax.tree.leaves(opt_l), jax.tree.leaves(opt_state2),
        )

    def test_load_without_templates(self, tmp_path):
        model, sup, x, _ = tiny_setup()
        fns = make_step_fns(model, make_optimizer(1e-3), "mse")
        params, opt_state = fns.init(jax.random.key(0), sup, x)
        path = str(tmp_path / "t.ckpt")
        save_checkpoint(path, params, opt_state, {"epoch": 1})
        _, params_l, _ = load_checkpoint(path)
        out_a = model.apply(params, sup, x)
        out_b = model.apply(jax.tree.map(jnp.asarray, params_l), sup, x)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), rtol=1e-6)

    @pytest.mark.slow
    def test_async_writes_identical_files_and_surfaces_errors(self, tmp_path):
        """Async checkpointing is a pure IO-scheduling change: byte-identical
        files vs sync mode, and worker failures surface at flush."""
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.experiment import build_trainer

        loaded = {}
        for label, flag in (("sync", False), ("async", True)):
            cfg = preset("smoke")
            cfg.data.n_timesteps = 24 * 7 * 2 + 48
            cfg.train.epochs = 2
            cfg.train.async_checkpoint = flag
            cfg.train.out_dir = str(tmp_path / label)
            trainer = build_trainer(cfg, verbose=False)
            trainer.train()  # flushes pending writes before returning
            loaded[label] = load_checkpoint(str(tmp_path / label / "best.ckpt"))
        meta_s, params_s, opt_s = loaded["sync"]
        meta_a, params_a, opt_a = loaded["async"]
        # identical state; meta differs only by the flag inside the config
        jax.tree.map(np.testing.assert_array_equal, params_a, params_s)
        jax.tree.map(np.testing.assert_array_equal, opt_a, opt_s)
        assert meta_a["epoch"] == meta_s["epoch"]
        assert meta_a["best_val"] == meta_s["best_val"]

        # a failing write is re-raised on flush, not swallowed
        cfg = preset("smoke")
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.train.epochs = 1
        cfg.train.out_dir = str(tmp_path / "err")
        trainer = build_trainer(cfg, verbose=False)
        trainer._write(str(tmp_path / "no_such_dir" / "x.ckpt"), b"data")
        with pytest.raises(RuntimeError, match="background checkpoint"):
            trainer.flush_checkpoints()

    def test_bad_magic_raises(self, tmp_path):
        path = tmp_path / "junk.ckpt"
        path.write_bytes(b"not a checkpoint")
        with pytest.raises(ValueError, match="not a stmgcn-tpu checkpoint"):
            load_checkpoint(str(path))


def small_trainer(tmp_path, epochs=3, patience=10, shuffle=False, **model_kw):
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 60, seed=1)
    dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    from stmgcn_tpu.ops import SupportConfig

    sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8, **model_kw)
    return Trainer(model, dataset, sup, n_epochs=epochs, patience=patience,
                   shuffle=shuffle, batch_size=16, out_dir=str(tmp_path),
                   verbose=False)


class TestTrainer:
    def test_train_writes_history_and_checkpoints(self, tmp_path):
        tr = small_trainer(tmp_path, epochs=2)
        hist = tr.train()
        assert len(hist["train"]) == 2
        assert os.path.exists(tr.best_path) and os.path.exists(tr.latest_path)
        lines = [json.loads(l) for l in open(tmp_path / "history.jsonl")]
        assert [l["epoch"] for l in lines] == [1, 2]
        meta, _, _ = load_checkpoint(tr.best_path)
        assert meta["normalizer"]["kind"] == "minmax"

    @pytest.mark.slow
    def test_cosine_schedule_trains_and_resumes_step_count(self, tmp_path):
        """The schedule's step counter lives in opt_state, so --resume
        continues the decay where the checkpoint left it."""
        data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 60, seed=1)
        dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24))
        from stmgcn_tpu.ops import SupportConfig

        sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
        model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                       lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
        kw = dict(n_epochs=2, batch_size=16, out_dir=str(tmp_path),
                  lr_schedule="cosine", warmup_epochs=0.5,
                  min_lr_fraction=0.05, verbose=False)
        tr = Trainer(model, dataset, sup, **kw)
        spe = tr._train_steps_per_epoch()
        hist = tr.train()
        assert np.isfinite(hist["train"]).all()
        counts = [
            int(leaf)
            for leaf in jax.tree.leaves(tr.opt_state)
            if np.ndim(leaf) == 0 and np.issubdtype(np.asarray(leaf).dtype, np.integer)
        ]
        assert 2 * spe in counts  # schedule stepped once per batch

        restored = Trainer(model, dataset, sup, **kw)
        restored.restore(tr.latest_path)
        counts = [
            int(leaf)
            for leaf in jax.tree.leaves(restored.opt_state)
            if np.ndim(leaf) == 0 and np.issubdtype(np.asarray(leaf).dtype, np.integer)
        ]
        assert 2 * spe in counts  # resume continues, not restarts, the decay

    def test_early_stopping_patience(self, tmp_path, monkeypatch):
        tr = small_trainer(tmp_path, epochs=50, patience=2)
        # scripted losses: improves once, then never again
        script = iter([1.0, 0.5, 1.0, 0.9, 1.0, 0.8, 1.0, 0.7, 1.0, 0.6])
        monkeypatch.setattr(tr, "_run_epoch", lambda mode, train: next(script))
        tr.train()
        assert tr.epoch == 3  # epoch1 improve; epochs 2,3 fail -> patience 2 exhausted
        assert tr.best_val == 0.5

    def test_patience_resets_on_improvement(self, tmp_path, monkeypatch):
        tr = small_trainer(tmp_path, epochs=50, patience=2)
        script = iter([1.0, 0.5, 1.0, 0.6, 1.0, 0.4, 1.0, 0.5, 1.0, 0.45, 1.0, 0.41])
        monkeypatch.setattr(tr, "_run_epoch", lambda mode, train: next(script))
        tr.train()
        # improvements at epochs 1 and 3 reset patience; epochs 4,5 fail -> stop at 5
        assert tr.epoch == 5
        assert tr.best_val == 0.4

    def test_top_k_checkpoint_retention(self, tmp_path, monkeypatch):
        tr = small_trainer(tmp_path, epochs=50, patience=50)
        tr.top_k = 2
        script = iter([1.0, 0.9, 1.0, 0.7, 1.0, 0.5, 1.0, 0.3, 1.0, 0.2,
                       1.0, 1.9, 1.0, 1.9, 1.0, 1.9])
        monkeypatch.setattr(tr, "_run_epoch", lambda mode, train: next(script))
        tr.n_epochs = 8
        tr.train()
        import glob

        kept = sorted(glob.glob(str(tmp_path / "best_e*.ckpt")))
        # five improvements (epochs 1-5); only the two best snapshots remain
        assert [os.path.basename(p) for p in kept] == ["best_e4.ckpt", "best_e5.ckpt"]

    @pytest.mark.slow
    def test_resume_continues_epoch_count(self, tmp_path):
        tr = small_trainer(tmp_path, epochs=2)
        tr.train()
        tr2 = small_trainer(tmp_path, epochs=4)
        meta = tr2.restore()
        assert meta["epoch"] == 2
        hist = tr2.train()
        assert len(hist["train"]) == 2  # epochs 3 and 4 only
        assert tr2.epoch == 4

    @pytest.mark.slow
    def test_same_seed_reproduces_trajectory(self, tmp_path):
        # shuffle=True exercises the seeded (seed, epoch) permutation stream —
        # the path a reproducibility regression would actually hit
        a = small_trainer(tmp_path / "a", epochs=2, shuffle=True)
        hist_a = a.train()
        b = small_trainer(tmp_path / "b", epochs=2, shuffle=True)
        hist_b = b.train()
        np.testing.assert_array_equal(hist_a["train"], hist_b["train"])
        np.testing.assert_array_equal(hist_a["validate"], hist_b["validate"])
        jax.tree.map(np.testing.assert_array_equal, a.params, b.params)

    def test_test_reports_denormalized_metrics(self, tmp_path):
        tr = small_trainer(tmp_path, epochs=1)
        tr.train()
        res = tr.test(modes=("test",))
        assert set(res["test"]) == {"mse", "rmse", "mae", "mape", "pcc"}
        # denormalized scale: RMSE should be in raw demand units (>> normalized 2-range)
        assert res["test"]["rmse"] > 1.0


class TestConfigAndExperiment:
    def test_presets_build(self):
        for name in ("smoke", "default", "scaled", "multicity", "longhorizon"):
            cfg = preset(name)
            assert cfg.name == name
            assert ExperimentConfig.from_dict(cfg.to_dict()).to_dict() == cfg.to_dict()

    def test_unknown_preset_raises(self):
        with pytest.raises(ValueError):
            preset("nope")

    def test_data_override_keeps_per_city_fields_consistent(self):
        cfg = preset("multicity")
        # overriding n_cities alone drops the now-mismatched tuples
        cfg.data.override(n_cities=1)
        assert cfg.data.city_rows is None and cfg.data.city_timesteps is None
        # replacing them in the same call keeps the replacements
        cfg2 = preset("multicity")
        cfg2.data.override(n_cities=3, city_rows=(4, 3, 5))
        assert cfg2.data.city_rows == (4, 3, 5)
        assert cfg2.data.city_timesteps is None  # length-2 tuple dropped
        # matching lengths survive untouched
        cfg3 = preset("multicity")
        cfg3.data.override(rows=4)
        assert cfg3.data.city_rows == (12, 10)
        with pytest.raises(AttributeError):
            preset("multicity").data.override(no_such_field=1)

    def test_build_dataset_multicity(self):
        """The multicity preset is heterogeneous: per-city N/T/graphs."""
        cfg = preset("multicity")
        ds = build_dataset(cfg)
        assert ds.n_cities == 2 and ds.heterogeneous
        assert ds.city_n_nodes == [144, 100]
        assert ds.mode_size("train") == sum(
            c.mode_size("train") for c in ds.cities
        )
        x0, _ = ds.city_arrays("train", 0)
        assert x0.shape[2] == 144

    def test_build_dataset_multicity_homogeneous(self):
        """Same-shape cities still pool into one homogeneous dataset."""
        cfg = preset("multicity")
        cfg.data.city_rows = None
        cfg.data.city_timesteps = None
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        ds = build_dataset(cfg)
        assert ds.n_cities == 2 and not ds.heterogeneous
        assert ds.mode_size("train") == ds.split.mode_len["train"] * 2
        x, y = ds.arrays("train")
        assert x.shape[0] == ds.mode_size("train")

    @pytest.mark.slow
    def test_multicity_percity_graphs_train_end_to_end(self, tmp_path):
        """BASELINE config 4 with *different* adjacencies per city: supports
        become a CitySupports and the trainer applies the right stack per
        batch (VERDICT round-1 missing #5)."""
        from stmgcn_tpu.experiment import build_supports
        from stmgcn_tpu.train import CitySupports

        cfg = preset("multicity")
        cfg.data.city_rows = (4, 3)
        cfg.data.city_timesteps = (24 * 7 * 2 + 24, 24 * 7 * 2)
        cfg.mesh.dp = 1  # single device keeps this test light; the dp-mesh
        cfg.train.epochs = 2  # variant runs in tests/test_parallel.py
        cfg.train.out_dir = str(tmp_path)
        ds = build_dataset(cfg)
        assert not ds.shared_graphs
        sup = build_supports(cfg, ds)
        assert isinstance(sup, CitySupports) and len(sup) == 2
        assert not np.array_equal(
            np.asarray(sup.for_city(0)), np.asarray(sup.for_city(1))
        )
        tr = build_trainer(cfg, verbose=False)
        hist = tr.train()
        assert np.isfinite(hist["train"]).all()
        assert np.isfinite(tr.test(modes=("test",))["test"]["rmse"])

    @pytest.mark.slow
    def test_prefetch_does_not_change_results(self, tmp_path):
        """Placement lookahead is a pure pipelining change: identical loss
        trajectories with prefetch disabled, default, and deep."""
        losses = {}
        for pf in (0, 1, 3):
            cfg = preset("smoke")
            cfg.data.n_timesteps = 24 * 7 * 2 + 48
            cfg.train.epochs = 2
            cfg.train.prefetch = pf
            cfg.train.out_dir = str(tmp_path / f"pf{pf}")
            losses[pf] = build_trainer(cfg, verbose=False).train()
        np.testing.assert_allclose(losses[0]["validate"], losses[1]["validate"])
        np.testing.assert_allclose(losses[0]["validate"], losses[3]["validate"])

    def test_multicity_shared_graphs_knob(self):
        cfg = preset("multicity")
        cfg.data.city_rows = None  # shared graphs need same-shape cities
        cfg.data.city_timesteps = None
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.data.shared_graphs = True
        assert build_dataset(cfg).shared_graphs

    def test_percity_graphs_reject_mesh_sparse_and_banded(self):
        from stmgcn_tpu.experiment import route_supports

        cfg = preset("multicity")
        cfg.data.city_rows = (4, 3)
        cfg.data.city_timesteps = (24 * 7 * 2 + 24, 24 * 7 * 2)
        cfg.model.sparse = True
        ds = build_dataset(cfg)
        with pytest.raises(ValueError, match="per-city"):
            route_supports(cfg, ds)

    def test_build_trainer_smoke_config(self, tmp_path):
        cfg = preset("smoke")
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.train.epochs = 1
        cfg.train.out_dir = str(tmp_path)
        tr = build_trainer(cfg, verbose=False)
        hist = tr.train()
        assert len(hist["train"]) == 1

    def test_supports_shape_from_config(self):
        cfg = preset("smoke")
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        ds = build_dataset(cfg)
        sup = build_supports(cfg, ds)
        assert sup.shape == (1, 3, 100, 100)

    def test_cli_overrides(self):
        from stmgcn_tpu.cli import build_parser, config_from_args

        args = build_parser().parse_args(
            ["--preset", "smoke", "--epochs", "7", "--lr", "0.01",
             "-cpt", "6", "2", "1", "--kernel", "localpool", "--cheb-k", "1"]
        )
        cfg = config_from_args(args)
        assert cfg.train.epochs == 7 and cfg.train.lr == 0.01
        assert (cfg.data.serial_len, cfg.data.daily_len, cfg.data.weekly_len) == (6, 2, 1)
        assert cfg.model.kernel_type == "localpool" and cfg.model.K == 1
