"""Branch model parallelism composed with the loop-layout support plans.

Round-4 rejected ``mesh.branch > 1`` with any active region strategy or
sparse supports (the loop layouts had no stacked branch axis to shard).
Round 5 lifts both: ``route_supports`` stacks every branch's supports
into ONE uniform operand — banded strips at a common halo
(``parallel.banded.branch_stack``) or block-CSR at a common block-column
width (``parallel.sparse.branch_stack_sparse``) — and the model runs
ONE vmapped Branch whose vmapped axis is the mesh's ``branch`` axis
(``nn.vmap(..., spmd_axis_name='branch')``). The inner shard_maps (ring
halo exchange / sharded SpMM) then run per branch group over ``region``
while the branch dim shards away, so the Pallas SpMM never needs a
graph-axis batching rule. Contract: identical losses/trajectories vs
the dense single-device reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.experiment import build_dataset, route_supports
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.parallel import (
    BandedSupports,
    MeshPlacement,
    ShardSpec,
    branch_stack,
    build_mesh,
)
from stmgcn_tpu.train import make_optimizer, make_step_fns


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return jax.devices()[:8]


def _band_adj(n: int, w: int, seed: int) -> np.ndarray:
    """Symmetric 0/1 adjacency with every edge within index distance w."""
    rng = np.random.default_rng(seed)
    a = np.zeros((n, n), np.float32)
    for d in range(1, w + 1):
        band = (rng.random(n - d) < 0.7).astype(np.float32)
        a += np.diag(band, d) + np.diag(band, -d)
    return a


def _band_supports(M, K, N, w, seed=0):
    """M branches of K random band matrices (bandwidth exactly <= w)."""
    rng = np.random.default_rng(seed)
    sup = np.zeros((M, K, N, N), np.float32)
    for m in range(M):
        for k in range(K):
            for d in range(-w, w + 1):
                sup[m, k] += np.diag(
                    rng.normal(size=N - abs(d)).astype(np.float32) * 0.2, d
                )
    return sup


class TestBranchStack:
    def test_common_halo_and_shapes(self):
        sup = _band_supports(M=2, K=3, N=16, w=2)
        sup[1, 0] += np.diag(np.ones(16 - 4, np.float32), 4)  # branch 1 wider
        stacked = branch_stack([sup[0], sup[1]], 2)
        assert isinstance(stacked, BandedSupports) and stacked.branch_stacked
        assert stacked.halo == 4  # max bandwidth across branches
        assert stacked.strips.shape == (2, 2, 3, 8, 8 + 2 * 4)
        assert stacked.n_supports == 3 and stacked.n_shards == 2

    def test_plain_form_properties_unchanged(self):
        from stmgcn_tpu.parallel import banded_decompose

        b = banded_decompose(_band_supports(1, 3, 16, 2)[0], 2)
        assert not b.branch_stacked
        assert b.n_supports == 3 and b.n_shards == 2


class TestRoutingWithBranchAxis:
    def _cfg(self, branch=2, halo=None):
        cfg = preset("smoke")
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.model.m_graphs = 2
        cfg.mesh.dp, cfg.mesh.region, cfg.mesh.branch = 2, 2, branch
        cfg.mesh.region_strategy = "auto"
        cfg.mesh.halo = halo
        return cfg

    def test_all_banded_branches_stack(self, eight_devices):
        cfg = self._cfg(halo=8)
        ds = build_dataset(cfg)
        n = ds.n_nodes
        ds.adjs = {"g0": _band_adj(n, 2, 1), "g1": _band_adj(n, 3, 2)}
        sup, modes = route_supports(cfg, ds)
        assert modes == ("banded", "banded")
        assert isinstance(sup, BandedSupports) and sup.branch_stacked
        assert sup.strips.shape[0] == 2  # M leading axis

    def test_over_budget_branch_raises_or_falls_back(self, eight_devices):
        cfg = self._cfg(halo=2)
        ds = build_dataset(cfg)
        n = ds.n_nodes
        # branch 1 reaches distance n//2 — beyond any halo=2 budget
        ds.adjs = {"g0": _band_adj(n, 1, 1), "g1": _band_adj(n, n // 2, 2)}
        cfg.mesh.region_strategy = "banded"
        with pytest.raises(ValueError, match="every branch banded"):
            route_supports(cfg, ds)
        # 'auto' falls back to the all-dense GSPMD branch plan instead
        cfg.mesh.region_strategy = "auto"
        _, modes = route_supports(cfg, ds)
        assert modes is None

    def test_sparse_with_branch_stacks(self, eight_devices):
        from stmgcn_tpu.parallel import ShardedBlockSparse

        cfg = self._cfg()
        cfg.model.sparse = True
        ds = build_dataset(cfg)
        sup, modes = route_supports(cfg, ds)
        assert modes == ("sparse", "sparse")
        assert isinstance(sup, ShardedBlockSparse) and sup.branch_stacked
        assert sup.data.shape[0] == 2  # M leading axis


@pytest.mark.slow
class TestBranchStackedParity:
    """Composed plans == dense single-device reference, same params."""

    @pytest.mark.parametrize("mode", ["banded", "sparse"])
    def test_forward_and_training_trajectory(self, eight_devices, mode):
        rng = np.random.default_rng(0)
        M, K, N, B, T, w = 2, 3, 16, 8, 5, 2
        if mode == "banded":
            dense = _band_supports(M, K, N, w)
        else:  # arbitrary sparse structure (block-CSR path)
            dense = (
                (rng.random((M, K, N, N)) < 0.3)
                * rng.normal(size=(M, K, N, N))
                * 0.2
            ).astype(np.float32)
        x = rng.standard_normal((B, T, N, 1)).astype(np.float32)
        y = (rng.standard_normal((B, N, 1)) * 0.1).astype(np.float32)
        mask = np.ones(B, np.float32)

        mesh = build_mesh(dp=2, region=2, branch=2)
        pl = MeshPlacement(mesh)
        kw = dict(m_graphs=M, n_supports=K, seq_len=T, input_dim=1,
                  lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8)
        ref = STMGCN(**kw)
        composed = STMGCN(**kw, support_modes=(mode,) * M,
                          shard_spec=ShardSpec(mesh))
        if mode == "banded":
            stacked_host = branch_stack(list(dense), 2)
        else:
            from stmgcn_tpu.parallel import branch_stack_sparse

            stacked_host = branch_stack_sparse(dense, 2)

        params = ref.init(jax.random.key(0), jnp.asarray(dense), jnp.asarray(x))
        want = ref.apply(params, jnp.asarray(dense), jnp.asarray(x))
        stacked = pl.put(stacked_host, "supports")
        got = jax.jit(composed.apply)(
            pl.put(params, "state"), stacked, pl.put(x, "x")
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

        fns_r = make_step_fns(ref, make_optimizer(1e-2, 1e-4), "mse")
        p, o = fns_r.init(jax.random.key(0), jnp.asarray(dense), jnp.asarray(x))
        single = []
        for _ in range(3):
            p, o, loss = fns_r.train_step(
                p, o, jnp.asarray(dense), jnp.asarray(x),
                jnp.asarray(y), jnp.asarray(mask),
            )
            single.append(float(loss))

        fns = make_step_fns(composed, make_optimizer(1e-2, 1e-4), "mse")
        x_m, y_m, mask_m = pl.put(x, "x"), pl.put(y, "y"), pl.put(mask, "mask")
        pm, om = fns.init(jax.random.key(0), stacked, x_m)
        pm, om = pl.put(pm, "state"), pl.put(om, "state")
        mesh_losses = []
        for _ in range(3):
            pm, om, loss = fns.train_step(pm, om, stacked, x_m, y_m, mask_m)
            mesh_losses.append(float(loss))
        np.testing.assert_allclose(mesh_losses, single, rtol=1e-5)
        # the stacked branch params genuinely shard over the branch axis
        wh = pm["params"]["branches"]["cg_lstm"]["lstm"]["wh_0"]
        assert wh.sharding.spec[0] == "branch"


@pytest.mark.slow
def test_trainer_end_to_end_sparse_branch_mesh(eight_devices, tmp_path):
    """Full build_trainer wiring on a (2,2,2) mesh with sparse supports:
    routing -> ShardSpec -> branch-stacked placement -> one epoch."""
    from stmgcn_tpu.experiment import build_trainer

    cfg = preset("smoke")
    cfg.data.n_timesteps = 24 * 7 * 2 + 24
    cfg.model.m_graphs = 2
    cfg.model.sparse = True
    cfg.train.epochs = 1
    cfg.train.batch_size = 8
    cfg.train.out_dir = str(tmp_path)
    cfg.mesh.dp, cfg.mesh.region, cfg.mesh.branch = 2, 2, 2
    trainer = build_trainer(cfg, verbose=False)
    # pin the intended path: a later fallback-to-dense would still train
    # finite losses, silently hollowing this test out
    assert trainer.model.branch_modes() == ("sparse", "sparse")
    assert trainer.supports.branch_stacked
    hist = trainer.train()
    assert np.isfinite(hist["train"][0])
    assert np.isfinite(trainer.test(modes=("test",))["test"]["rmse"])


class TestRebuildLayout:
    def test_sparse_branch_checkpoint_rebuilds_vmapped(self, eight_devices):
        """A sparse + branch>1 config trains in the vmapped stacked layout;
        its mesh-less rebuild (Forecaster path: build_model with
        support_modes=None, dense supports) must produce the SAME param
        tree, not the sparse loop layout."""
        from stmgcn_tpu.experiment import build_model

        cfg = preset("smoke")
        cfg.model.m_graphs = 2
        cfg.model.sparse = True
        cfg.mesh.dp, cfg.mesh.region, cfg.mesh.branch = 2, 2, 2

        trained = build_model(
            cfg, 1, support_modes=("sparse", "sparse"),
            shard_spec=ShardSpec(build_mesh(dp=2, region=2, branch=2)),
        )
        rebuilt = build_model(cfg, 1)  # Forecaster's call: no mesh, no modes
        assert rebuilt.vmap_branches and not rebuilt.sparse
        dense = _band_supports(2, cfg.model.n_supports, 16, 2)
        x = jnp.zeros((2, cfg.data.seq_len, 16, 1))
        p = rebuilt.init(jax.random.key(0), jnp.asarray(dense), x)
        assert "branches" in p["params"]  # vmapped stacked layout
        from stmgcn_tpu.parallel import branch_stack_sparse

        stacked = branch_stack_sparse(dense, 2)
        p2 = trained.init(jax.random.key(0), stacked, x)
        assert jax.tree_util.tree_structure(p) == jax.tree_util.tree_structure(p2)


class TestModelValidation:
    def test_branch_stacked_needs_all_banded_modes(self):
        mesh = build_mesh(dp=2, region=2, branch=2)
        sup = branch_stack(list(_band_supports(2, 3, 16, 2)), 2)
        model = STMGCN(m_graphs=2, n_supports=3, seq_len=5, input_dim=1,
                       lstm_hidden_dim=4, lstm_num_layers=1, gcn_hidden_dim=4,
                       support_modes=("banded", "dense"),
                       shard_spec=ShardSpec(mesh))
        x = jnp.zeros((2, 5, 16, 1))
        with pytest.raises(ValueError, match="banded"):
            model.init(jax.random.key(0), sup, x)

    def test_branch_count_mismatch_raises(self):
        mesh = build_mesh(dp=2, region=2, branch=2)
        sup = branch_stack(list(_band_supports(2, 3, 16, 2)), 2)
        model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                       lstm_hidden_dim=4, lstm_num_layers=1, gcn_hidden_dim=4,
                       support_modes=("banded",) * 3,
                       shard_spec=ShardSpec(mesh))
        x = jnp.zeros((2, 5, 16, 1))
        with pytest.raises(ValueError, match="branches"):
            model.init(jax.random.key(0), sup, x)
