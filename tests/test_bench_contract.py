"""The bench.py driver contract, pinned by tests.

bench.py is the one file the driver parses every round: it must print
EXACTLY one JSON line and exit 0 on any environment trouble. A
regression here silently costs a round its benchmark record (round 1
lost its record to rc=2), so the contract gets the same regression
protection as the model code. Runs at tiny shapes on pinned CPU via the
same subprocess runner the sweep tools use.
"""

import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benchmarks"),
)
from variants import run_bench as _run_bench  # noqa: E402

pytestmark = pytest.mark.slow

TINY = {
    "STMGCN_BENCH_PLATFORM": "cpu",
    "STMGCN_BENCH_ROWS": "4",
    "STMGCN_BENCH_BATCH": "8",
    "STMGCN_BENCH_WARMUP": "1",
    "STMGCN_BENCH_ITERS": "2",
    # a private lock path: the contract tests must not block behind (or
    # fail because of) a live tunnel-recovery loop holding the real
    # host-wide lock for minutes at a time
    "STMGCN_BENCH_LOCK_PATH": "/tmp/stmgcn_bench_test.lock",
}

#: ambient STMGCN_* (sweep leftovers, tuning exports) must not leak into
#: the children — these tests pin the contract, not the shell's state
CLEAN_ENV = {k: v for k, v in os.environ.items() if not k.startswith("STMGCN_")}


def run_bench(env_extra: dict, timeout: float) -> dict:
    return _run_bench(env_extra, base_env=CLEAN_ENV, timeout=timeout)


def test_canonical_record_shape():
    rec = run_bench({**TINY, "STMGCN_BENCH_DTYPE": "float32"}, timeout=420)
    assert rec.get("error") is None, rec
    assert rec["metric"] == "region-timesteps/sec/chip"
    assert rec["value"] > 0 and rec["unit"] == "region-timesteps/s"
    # both XLA schedules + the fused superstep measured even at the tiny point
    assert set(rec["variants"]) == {
        "float32/plain", "float32/tuned", "float32/superstep",
    }
    assert rec["variants"]["float32/superstep"]["s_steps"] >= 1
    assert rec["variants"]["float32/superstep"]["step_ms"] > 0
    assert rec["baseline"]["value"] is not None  # anchor provenance embedded
    # host-load provenance: a contended record must be flaggable in-band
    load = rec["host_load"]
    assert load["lock"]["acquired"] is True
    for snap in (load["before"], load["after"]):
        assert snap["nproc"] >= 1
        assert isinstance(snap["competing_python"], list)
    # auxiliary evidence files ride along with platform provenance — both
    # are committed (cpu-fallback or better), so attachment must fire
    for key in ("scaled_accuracy", "serving"):
        assert rec[key]["platform"] in ("tpu", "cpu-fallback"), rec.get(key)


def test_scaled_mode_record():
    rec = run_bench(
        {**TINY, "STMGCN_BENCH_MODE": "scaled", "STMGCN_BENCH_ROWS": "6"},
        timeout=420,
    )
    assert rec.get("error") is None, rec
    assert rec["operating_point"] == "scaled-n2500"
    # off-TPU only the dense leg runs (sparse would be interpret-mode)
    assert set(rec["variants"]) == {"dense"}
    assert rec["value"] > 0 and rec["vs_baseline"] is None


def test_pallas_off_tpu_refuses_parsably():
    rec = run_bench(
        {**TINY, "STMGCN_BENCH_LSTM_BACKEND": "pallas"}, timeout=240
    )
    assert rec["value"] == 0.0
    assert "pallas" in rec["error"] and "TPU" in rec["error"]


def test_stdout_stays_one_json_line_when_probe_retries():
    """The driver parses bench stdout as exactly one JSON line; the
    backend-probe retry diagnostics must land on stderr (the round-5
    record tail showed what merged streams look like — the in-band
    record must never depend on the driver splitting them)."""
    import json
    import subprocess

    env = {**CLEAN_ENV, **TINY, "STMGCN_BENCH_DTYPE": "float32",
           # the probe child is a FRESH jax init, so a poisoned platform
           # fails every probe attempt (deterministically, unlike a short
           # watchdog on a fast host) — while the bench parent recovers:
           # the fallback path rewrites JAX_PLATFORMS=cpu before any
           # device use of its own
           "JAX_PLATFORMS": "no_such_platform"}
    env.pop("STMGCN_BENCH_PLATFORM")  # pinning would skip the probe
    bench = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
    )
    out = subprocess.run(
        [sys.executable, bench], env=env, capture_output=True, text=True,
        timeout=420,
    )
    assert out.returncode == 0, out.stderr[-500:]
    lines = out.stdout.strip().splitlines()
    assert len(lines) == 1, f"stdout not a single record line: {out.stdout!r}"
    rec = json.loads(lines[0])
    assert rec["metric"] == "region-timesteps/sec/chip"
    assert rec["platform"] == "cpu-fallback" and rec["value"] > 0
    assert "retrying" in out.stderr  # the diagnostics went to stderr


def test_bad_dtype_fails_loudly():
    """Invalid operator configuration must fail loudly, not fall back —
    run_bench surfaces the child's nonzero exit as an error record."""
    rec = run_bench({**TINY, "STMGCN_BENCH_DTYPE": "float64"}, timeout=240)
    assert rec.get("error", "").startswith("bench exited"), rec
    assert "value" not in rec  # no throughput number from a refused config


def test_serving_bench_record_contract(tmp_path):
    """benchmarks/serving_latency.py: one JSON line on stdout, with the
    serving-engine evidence the driver and README table consume — legs
    with latency percentiles, the queue/device split, and both
    acceptance ratios."""
    import json
    import subprocess

    out_json = str(tmp_path / "serving.json")
    env = {
        **CLEAN_ENV,
        "JAX_PLATFORMS": "cpu",
        "STMGCN_SERVE_ROWS": "3",
        "STMGCN_SERVE_BATCH": "4",
        "STMGCN_SERVE_CLIENTS": "4",
        "STMGCN_SERVE_PER_CLIENT": "10",
        "STMGCN_SERVE_ITERS": "5",
        "STMGCN_SERVE_OUT": out_json,
        "STMGCN_BENCH_LOCK_PATH": "/tmp/stmgcn_serve_test.lock",
    }
    bench = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
        "serving_latency.py",
    )
    proc = subprocess.run(
        [sys.executable, bench], env=env, capture_output=True, text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1, f"stdout not a single record line: {proc.stdout!r}"
    rec = json.loads(lines[0])
    assert rec["platform"] in ("tpu", "cpu-fallback")
    assert rec["captured_at"]
    # every leg carries warmup-excluded latency percentiles + throughput
    for leg in ("forecaster/b1", "forecaster/b4", "engine/b1", "engine/b4",
                "engine/microbatch4"):
        stats = rec["legs"][leg]
        assert stats["p50_ms"] > 0 and stats["p99_ms"] >= stats["p50_ms"]
        assert stats["predictions_per_sec"] > 0
    # both acceptance ratios present (values are operating-point-dependent)
    assert set(rec["speedup"]) == {"b16_vs_b1", "microbatch_vs_sequential_b1"}
    # per-bucket telemetry splits queue wait from device time
    totals = rec["engine_stats"]["totals"]
    # stats reset after warmup: exactly the 4 clients x 10 measured requests
    assert totals["requests"] == 40
    assert totals["queue_wait_ms_mean"] is not None
    assert totals["device_ms_mean"] is not None
    for stats in rec["engine_stats"]["buckets"].values():
        assert {"queue_wait_ms", "device_ms", "latency_ms",
                "pad_waste"} <= set(stats)
    assert rec["host_load"]["lock"]["acquired"] is True


def test_serve_bench_soak_record_contract():
    """``serve-bench --soak``: the one-JSON-line stdout contract holds
    with the overload leg on, and the soak record carries the operability
    evidence — typed shed counts, zero hung clients, and the mid-soak
    hot-swap with bit parity on both generations."""
    import json
    import subprocess

    env = {**CLEAN_ENV, "JAX_PLATFORMS": "cpu",
           "STMGCN_BENCH_LOCK_PATH": "/tmp/stmgcn_serve_test.lock"}
    cmd = [
        sys.executable, "-m", "stmgcn_tpu.cli", "serve-bench",
        "--rows", "3", "--batch", "4", "--buckets", "1,2,4",
        "--clients", "4", "--per-client", "4", "--iters", "5",
        "--warmup", "1", "--no-fleet",
        "--soak", "--soak-seconds", "1.0", "--soak-overload", "2.0",
    ]
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1, f"stdout not a single record line: {proc.stdout!r}"
    soak = json.loads(lines[0])["soak"]
    # offered load is fully accounted for: served, shed, or neither —
    # never a hung caller
    assert soak["hung_clients"] == 0
    assert (soak["admitted"] + sum(soak["shed"].values())
            <= soak["config"]["offered_requests"])
    assert soak["admitted"] > 0
    assert soak["calibration"]["per_dispatch_ms"] > 0
    assert soak["slo_target_ms"] > soak["config"]["deadline_ms"] > 0
    assert soak["admitted_latency_ms"]["p99"] is not None
    assert isinstance(soak["contended"], bool)
    # the mid-soak atomic swap landed and BOTH generations are bit-exact
    # against their reference predictors
    hs = soak["hot_swap"]
    assert hs["swap_error"] is None
    assert hs["swap_applied"] is True and hs["generation_after"] == 1
    assert hs["parity_gen0"] is True and hs["parity_gen1"] is True
