"""AOT export round-trip: serialized StableHLO artifact == live Forecaster.

Train-free: a freshly-initialized flagship plus a fitted normalizer is
enough to pin the contract (baked params, symbolic batch, normalize →
call → denormalize). The loaded side must not need the model code, so
the round-trip goes through the file, not the objects.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.data import DemandDataset, MinMaxNormalizer, WindowSpec, synthetic_dataset
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.export import ExportedForecaster, export_forecaster
from stmgcn_tpu.inference import Forecaster
from stmgcn_tpu.ops import SupportConfig


@pytest.fixture(scope="module")
def setup():
    cfg = preset("smoke")
    cfg.data.rows = 3
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 40, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    supports = np.asarray(
        SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(ds.adjs.values()),
        np.float32,
    )[: cfg.model.m_graphs]
    model = build_model(cfg, ds.n_feats)
    x = jnp.zeros((2, cfg.data.seq_len, ds.n_nodes, ds.n_feats), jnp.float32)
    params = model.init(jax.random.key(0), jnp.asarray(supports), x)
    norm = MinMaxNormalizer.fit(np.asarray(data.demand))
    fc = Forecaster(
        model, params, norm, cfg, {"input_dim": ds.n_feats, "n_nodes": ds.n_nodes}
    )
    return fc, supports, ds


def test_export_roundtrip_matches_forecaster(setup, tmp_path):
    fc, supports, ds = setup
    path = str(tmp_path / "model.stmgx")
    export_forecaster(fc, path, platforms=("cpu",))

    loaded = ExportedForecaster.load(path)
    rng = np.random.default_rng(1)
    hist = rng.uniform(0, 50, (4, fc.seq_len, ds.n_nodes, ds.n_feats)).astype(
        np.float32
    )
    np.testing.assert_allclose(
        loaded.predict(supports, hist),
        fc.predict(supports, hist),
        rtol=1e-5,
        atol=1e-4,
    )


def test_export_symbolic_batch(setup, tmp_path):
    """One artifact serves multiple batch sizes (symbolic batch dim)."""
    fc, supports, ds = setup
    path = str(tmp_path / "model.stmgx")
    export_forecaster(fc, path, platforms=("cpu",))
    loaded = ExportedForecaster.load(path)
    for b in (1, 3, 7):
        out = loaded.predict(
            supports, np.ones((b, fc.seq_len, ds.n_nodes, ds.n_feats), np.float32)
        )
        assert out.shape[0] == b and np.isfinite(out).all()


def test_export_validates_shapes(setup, tmp_path):
    fc, supports, ds = setup
    path = str(tmp_path / "model.stmgx")
    export_forecaster(fc, path, platforms=("cpu",))
    loaded = ExportedForecaster.load(path)
    with pytest.raises(ValueError, match="history must be"):
        loaded.predict(supports, np.ones((2, 99, ds.n_nodes, ds.n_feats), np.float32))
    with pytest.raises(ValueError, match="supports must be"):
        loaded.predict(supports[:, :1], np.ones((2, fc.seq_len, ds.n_nodes, ds.n_feats), np.float32))


def test_export_converts_sparse_checkpoint(setup, tmp_path):
    """A sparse-trained checkpoint (per-branch looped param layout) exports
    transparently: params are restacked to the dense vmapped layout and the
    artifact matches the dense model on the same weights."""
    import dataclasses

    import jax as _jax

    from stmgcn_tpu.models import to_looped_params

    fc, supports, ds = setup
    looped_params = to_looped_params(fc.params, fc.config.model.m_graphs)
    sparse_fc = Forecaster(
        dataclasses.replace(fc.model, sparse=True),
        _jax.tree.map(jnp.asarray, looped_params),
        fc.normalizer,
        fc.config,
        fc.derived,
    )
    path = str(tmp_path / "m.stmgx")
    export_forecaster(sparse_fc, path, platforms=("cpu",))
    hist = np.ones((2, fc.seq_len, ds.n_nodes, ds.n_feats), np.float32)
    np.testing.assert_allclose(
        ExportedForecaster.load(path).predict(supports, hist),
        fc.predict(supports, hist),
        rtol=1e-5,
        atol=1e-4,
    )


def test_export_pallas_backend_via_xla_clone(setup, tmp_path):
    """A pallas-backend forecaster exports through an xla clone of the same
    params (the kernel is a TPU-only custom call; the scan path is the
    same function — tests/test_pallas_lstm.py) and matches the xla export."""
    import dataclasses

    fc, supports, ds = setup
    pallas_fc = Forecaster(
        dataclasses.replace(fc.model, lstm_backend="pallas"),
        fc.params,
        fc.normalizer,
        fc.config,
        fc.derived,
    )
    path = str(tmp_path / "pallas.stmgx")
    export_forecaster(pallas_fc, path, platforms=("cpu",))
    hist = np.ones((2, fc.seq_len, ds.n_nodes, ds.n_feats), np.float32)
    np.testing.assert_allclose(
        ExportedForecaster.load(path).predict(supports, hist),
        fc.predict(supports, hist),
        rtol=1e-5,
        atol=1e-4,
    )


def test_export_module_is_lean():
    """The serving-side module must not pull the model stack — that's the
    point of the artifact (load + predict without flax/optax/models)."""
    import subprocess
    import sys as _sys

    out = subprocess.run(
        [
            _sys.executable,
            "-c",
            "import sys; import stmgcn_tpu.export; "
            "heavy = [m for m in sys.modules if m == 'flax' "
            "or m.startswith(('flax.', 'optax', 'stmgcn_tpu.models', "
            "'stmgcn_tpu.experiment', 'stmgcn_tpu.train'))]; "
            "print(','.join(heavy) or 'LEAN')",
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert out.stdout.strip().splitlines()[-1] == "LEAN", out.stdout + out.stderr


def test_export_rejects_bad_file(tmp_path):
    p = tmp_path / "junk.stmgx"
    p.write_bytes(b"not an artifact")
    with pytest.raises(ValueError, match="not an stmgcn-tpu export artifact"):
        ExportedForecaster.load(str(p))


def test_export_rejects_corrupt_length_field(tmp_path):
    """A lying 8-byte length must fail cleanly BEFORE any allocation."""
    import struct

    from stmgcn_tpu.export import _MAGIC

    p = tmp_path / "corrupt.stmgx"
    # Claims an 8 EiB blob; the file holds 4 bytes.
    p.write_bytes(_MAGIC + struct.pack("<Q", 1 << 62) + b"abcd")
    with pytest.raises(ValueError, match="truncated export artifact"):
        ExportedForecaster.load(str(p))


def test_export_rejects_trailing_garbage(setup, tmp_path):
    fc, supports, ds = setup
    path = str(tmp_path / "model.stmgx")
    export_forecaster(fc, path, platforms=("cpu",))
    with open(path, "ab") as f:
        f.write(b"\x00garbage appended after the final blob")
    with pytest.raises(ValueError, match="trailing garbage"):
        ExportedForecaster.load(path)
