"""Sparse end-to-end model tests: loop branches + Pallas SpMM path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.data import grid_adjacency
from stmgcn_tpu.experiment import build_trainer
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.ops.spmm import from_dense


def setup(N_side=12, M=2, B=4, T=5):
    rng = np.random.default_rng(0)
    adjs = []
    base = grid_adjacency(N_side)
    for m in range(M):
        a = base.copy()
        extra = (rng.random(a.shape) < 0.01).astype(np.float32)
        a = np.maximum(a, np.maximum(extra, extra.T))
        np.fill_diagonal(a, 0)
        adjs.append(a)
    dense = SupportConfig("chebyshev", 2).build_all(adjs)  # (M, 3, N, N)
    sparse = tuple(tuple(from_dense(dense[m, k]) for k in range(3)) for m in range(M))
    n = dense.shape[-1]
    x = jnp.asarray(rng.standard_normal((B, T, n, 1)).astype(np.float32))
    return dense, sparse, x


def model_kw(M, sparse=False, vmap=True):
    return dict(m_graphs=M, n_supports=3, seq_len=5, input_dim=1,
                lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8,
                sparse=sparse, vmap_branches=vmap)


class TestLoopVsVmap:
    def test_loop_dense_matches_vmap_dense(self):
        dense, _, x = setup()
        dense = jnp.asarray(dense)
        vmapped = STMGCN(**model_kw(2, vmap=True))
        params_v = vmapped.init(jax.random.key(0), dense, x)
        out_v = vmapped.apply(params_v, dense, x)

        looped = STMGCN(**model_kw(2, vmap=False))
        # map the stacked branch params onto the per-branch tree
        stacked = params_v["params"]["branches"]
        loop_params = {"params": {"head": params_v["params"]["head"]}}
        for m in range(2):
            loop_params["params"][f"branch_{m}"] = jax.tree.map(
                lambda a, m=m: a[m], stacked
            )
        out_l = looped.apply(loop_params, dense, x)
        np.testing.assert_allclose(np.asarray(out_l), np.asarray(out_v),
                                   rtol=2e-5, atol=2e-6)


class TestSparseModel:
    def test_sparse_matches_dense_loop_with_same_params(self):
        dense, sparse, x = setup()
        looped = STMGCN(**model_kw(2, vmap=False))
        params = looped.init(jax.random.key(0), jnp.asarray(dense), x)
        want = looped.apply(params, jnp.asarray(dense), x)

        sparse_model = STMGCN(**model_kw(2, sparse=True))
        got = sparse_model.apply(params, sparse, x)  # identical param tree
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_sparse_grad_and_training_step(self):
        from stmgcn_tpu.train import make_optimizer, make_step_fns

        dense, sparse, x = setup(B=4)
        y = jnp.asarray(
            np.random.default_rng(1).standard_normal((4, 144, 1)).astype(np.float32) * 0.1
        )
        model = STMGCN(**model_kw(2, sparse=True))
        fns = make_step_fns(model, make_optimizer(1e-2), "mse")
        params, opt_state = fns.init(jax.random.key(0), sparse, x)
        first = None
        for _ in range(5):
            params, opt_state, loss = fns.train_step(
                params, opt_state, sparse, x, y, jnp.ones(4)
            )
            first = first if first is not None else float(loss)
        assert np.isfinite(float(loss)) and float(loss) < first

    def test_wrong_group_count_raises(self):
        dense, sparse, x = setup()
        model = STMGCN(**model_kw(3, sparse=True))
        with pytest.raises(ValueError, match="support groups"):
            model.init(jax.random.key(0), sparse, x)


class TestSparseExperiment:
    def test_sparse_preset_trains_end_to_end(self, tmp_path):
        cfg = preset("smoke")
        cfg.model.sparse = True
        cfg.model.m_graphs = 1
        cfg.data.rows = 12
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.train.epochs = 1
        cfg.train.batch_size = 16
        cfg.train.out_dir = str(tmp_path)
        trainer = build_trainer(cfg, verbose=False)
        hist = trainer.train()
        assert np.isfinite(hist["train"][0])

    def test_sparse_plus_mesh_routes_sharded(self):
        # round 1 rejected this composition; it now routes to per-shard
        # block-CSR strips (full coverage in tests/test_sparse_mesh.py)
        from stmgcn_tpu.experiment import build_dataset, route_supports
        from stmgcn_tpu.parallel import ShardedBlockSparse

        cfg = preset("scaled")
        cfg.data.rows = 8
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.model.sparse = True
        sup, modes = route_supports(cfg, build_dataset(cfg))
        assert modes == ("sparse",) * 3
        assert all(isinstance(s, ShardedBlockSparse) for s in sup)

    def test_sparse_bf16_training_step(self):
        """bf16 compute over the sparse path: the SpMM kernels accumulate
        f32 and their VJP must return the cotangent in the *primal's*
        dtype — an f32 dx for a bf16 primal detonated dtype checks at the
        next slice transpose upstream (found on the scaled preset)."""
        from stmgcn_tpu.experiment import build_dataset, build_model, route_supports
        from stmgcn_tpu.train import make_optimizer, make_step_fns

        cfg = preset("scaled")
        cfg.data.rows = 6
        cfg.model.sparse = True
        cfg.train.batch_size = 2
        cfg.data.n_timesteps = 24 * 7 * 2 + 10
        cfg.mesh.dp = cfg.mesh.region = 1
        assert cfg.model.dtype == "bfloat16"  # the preset's point
        ds = build_dataset(cfg)
        supports, modes = route_supports(cfg, ds)
        model = build_model(cfg, ds.n_feats, modes, None)
        fns = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse")
        batch = next(ds.batches("train", 2, pad_last=True))
        x, y = jnp.asarray(batch.x), jnp.asarray(batch.y)
        mask = jnp.ones(len(batch), jnp.float32)
        params, opt = fns.init(jax.random.key(0), supports, x)
        _, _, loss = fns.train_step(params, opt, supports, x, y, mask)
        assert np.isfinite(float(loss))
