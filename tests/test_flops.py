"""FLOPs model / MFU accounting sanity (stmgcn_tpu/utils/flops.py)."""

import numpy as np
import pytest

from stmgcn_tpu.utils import device_peak_flops, mfu, stmgcn_step_flops


BASE = dict(
    batch=64,
    seq_len=12,
    n_nodes=256,
    n_feats=1,
    m_graphs=3,
    n_supports=3,
    lstm_hidden_dim=64,
    lstm_num_layers=3,
    gcn_hidden_dim=64,
)


def test_flops_positive_and_batch_linear():
    f1 = stmgcn_step_flops(**BASE)
    f2 = stmgcn_step_flops(**{**BASE, "batch": 128})
    assert f1 > 0
    assert f2 == pytest.approx(2 * f1)


def test_backward_is_3x_forward():
    fwd = stmgcn_step_flops(**BASE, backward=False)
    full = stmgcn_step_flops(**BASE, backward=True)
    assert full == pytest.approx(3 * fwd)


def test_quadratic_node_term_grows_with_n():
    # The K support matmuls are O(N^2) while the LSTM is O(N); their share
    # of the model must grow superlinearly with N — the dense-path blowup
    # SURVEY §2 quirk 8 flags (reference's dense (K,N,N) at GCN.py:6,95).
    def quad_share(n):
        f = stmgcn_step_flops(**{**BASE, "n_nodes": n}, backward=False)
        b, t = BASE["batch"], BASE["seq_len"]
        k, m, h = BASE["n_supports"], BASE["m_graphs"], BASE["lstm_hidden_dim"]
        quad = m * (2.0 * k * b * n * n * t + 2.0 * k * b * n * n * h)
        return quad / f

    assert quad_share(2500) > 5 * quad_share(64)
    assert quad_share(2500) > 0.3


@pytest.mark.slow
def test_flops_against_jax_cost_analysis():
    """Analytic forward FLOPs within ~2x of XLA's own cost analysis.

    Backends differ in counting convention (the CPU backend counts ~1 flop
    per MAC where the model counts 2) and XLA folds elementwise work into
    fusions, so exact equality is not expected — but the analytic model
    must be the same order, or the MFU number is not defensible.
    """
    import jax
    import jax.numpy as jnp

    from stmgcn_tpu.models import STMGCN

    cfg = dict(BASE, n_nodes=64)
    model = STMGCN(
        m_graphs=cfg["m_graphs"],
        n_supports=cfg["n_supports"],
        seq_len=cfg["seq_len"],
        input_dim=cfg["n_feats"],
        lstm_hidden_dim=cfg["lstm_hidden_dim"],
        lstm_num_layers=cfg["lstm_num_layers"],
        gcn_hidden_dim=cfg["gcn_hidden_dim"],
    )
    sup = jnp.asarray(np.random.default_rng(0).normal(size=(3, 3, 64, 64)), jnp.float32)
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(cfg["batch"], cfg["seq_len"], 64, 1)),
        jnp.float32,
    )
    params = model.init(jax.random.key(0), sup, x)
    lowered = jax.jit(lambda p, s, xx: model.apply(p, s, xx)).lower(params, sup, x)
    cost = lowered.compile().cost_analysis()
    xla_flops = cost.get("flops") if isinstance(cost, dict) else cost[0].get("flops")
    if not xla_flops:
        pytest.skip("backend reports no flops in cost_analysis")
    analytic = stmgcn_step_flops(**{**BASE, "n_nodes": 64}, backward=False)
    assert 0.4 < analytic / xla_flops < 3.0


def test_mfu_helpers():
    assert mfu(1e12, 1.0, 197e12) == pytest.approx(1 / 197)
    assert mfu(1e12, 1.0, None) is None
    # CPU devices have no TPU peak
    assert device_peak_flops() is None
