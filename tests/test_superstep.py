"""Superstep training: fused S-step scan parity and plumbing.

The superstep path is a pure dispatch-granularity change: for any
``steps_per_superstep`` it must compute bit-identical params, opt-state,
losses, and histories to the per-step loop, and fall back to that loop
wherever the fused on-device gather cannot apply (streamed data, per-city
graphs/models). Parity here is exact equality, not allclose — the scan
body IS the per-step body (train/step.py ``_raw_step_bodies``), so any
drift means the paths diverged structurally.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.experiment import build_trainer
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.train import make_optimizer, make_step_fns, make_superstep_fns

BATCH = 8
S = 3  # with the smoke slice below: 20 train batches -> 6 blocks + 2 remainder


def _train(tmp_path, s_steps, shuffle=False, placement="resident", epochs=2):
    cfg = preset("smoke")
    cfg.data.rows = 5
    cfg.data.n_timesteps = 24 * 7 * 2 + 60
    cfg.train.epochs = epochs
    cfg.train.batch_size = BATCH
    cfg.train.data_placement = placement
    cfg.train.shuffle = shuffle
    cfg.train.steps_per_superstep = s_steps
    cfg.train.out_dir = str(tmp_path / f"{placement}-s{s_steps}-{shuffle}")
    trainer = build_trainer(cfg, verbose=False)
    history = trainer.train()
    return trainer, history


def _assert_same_state(a, b):
    jax.tree.map(np.testing.assert_array_equal, a.params, b.params)
    jax.tree.map(
        np.testing.assert_array_equal,
        jax.tree.leaves(a.opt_state), jax.tree.leaves(b.opt_state),
    )


@pytest.mark.parametrize(
    "shuffle", [False, pytest.param(True, marks=pytest.mark.slow)]
)
def test_superstep_bit_identical_to_per_step(tmp_path, shuffle):
    base_tr, base_hist = _train(tmp_path, 1, shuffle)
    sup_tr, sup_hist = _train(tmp_path, S, shuffle)
    assert not base_tr._superstep_ready() and sup_tr._superstep_ready()
    assert sup_tr._superstep_fns is not None  # the fused path actually ran
    # coverage preconditions: full S-blocks AND a per-step remainder AND a
    # padded tail batch (n_real < B) — all three paths exercised
    batches = list(sup_tr.dataset.batches("train", BATCH, pad_last=True))
    assert len(batches) // S >= 1 and len(batches) % S != 0
    assert batches[-1].n_real < BATCH
    np.testing.assert_array_equal(base_hist["train"], sup_hist["train"])
    np.testing.assert_array_equal(base_hist["validate"], sup_hist["validate"])
    _assert_same_state(base_tr, sup_tr)


@pytest.mark.slow
def test_streamed_data_falls_back_per_step(tmp_path):
    """steps_per_superstep > 1 on the streaming path is inert: the gate
    refuses (no resident pool to gather from) and results are unchanged."""
    stream_tr, stream_hist = _train(tmp_path, 4, placement="stream")
    base_tr, base_hist = _train(tmp_path, 1, placement="resident")
    assert not stream_tr._superstep_ready()
    assert stream_tr._superstep_fns is None  # never even built
    np.testing.assert_array_equal(base_hist["train"], stream_hist["train"])
    _assert_same_state(base_tr, stream_tr)


def test_superstep_fns_match_looped_train_step():
    """Unit-level: one jitted superstep == S sequential train_step calls
    with host-side gathers, bit for bit (params, opt-state, every loss)."""
    rng = np.random.default_rng(0)
    m, n, t, b, s, pool = 2, 9, 5, 4, 3, 10
    sup = jnp.asarray(rng.standard_normal((m, 3, n, n)).astype(np.float32) * 0.2)
    model = STMGCN(m_graphs=m, n_supports=3, seq_len=t, input_dim=1,
                   lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
    x_all = jnp.asarray(rng.standard_normal((pool, t, n, 1)).astype(np.float32))
    y_all = jnp.asarray(
        rng.standard_normal((pool, n, 1)).astype(np.float32) * 0.1
    )
    optimizer = make_optimizer(1e-3, 1e-4)
    fns = make_step_fns(model, optimizer, "mse")
    sfns = make_superstep_fns(model, optimizer, "mse")
    params, opt_state = fns.init(jax.random.key(0), sup, x_all[:b])
    idx = rng.integers(0, pool, size=(s, b)).astype(np.int32)
    mask = np.ones((s, b), np.float32)
    mask[-1, -1] = 0.0  # a padded slot in the final microbatch

    # independent copies: both jitted paths donate (params, opt_state)
    p_ref = jax.tree.map(jnp.array, params)
    s_ref = jax.tree.map(jnp.array, opt_state)
    ref_losses = []
    for i in range(s):
        xb = jnp.take(x_all, jnp.asarray(idx[i]), axis=0)
        yb = jnp.take(y_all, jnp.asarray(idx[i]), axis=0)
        p_ref, s_ref, loss = fns.train_step(
            p_ref, s_ref, sup, xb, yb, jnp.asarray(mask[i])
        )
        ref_losses.append(np.asarray(loss))

    p_sup, s_sup, losses = sfns.train_superstep(
        params, opt_state, sup, x_all, y_all, jnp.asarray(idx),
        jnp.asarray(mask),
    )
    assert losses.shape == (s,)
    np.testing.assert_array_equal(
        np.asarray(losses), np.asarray(ref_losses, dtype=np.float32)
    )
    jax.tree.map(np.testing.assert_array_equal, p_sup, p_ref)
    jax.tree.map(
        np.testing.assert_array_equal,
        jax.tree.leaves(s_sup), jax.tree.leaves(s_ref),
    )


def test_cli_and_config_plumbing():
    from stmgcn_tpu.cli import build_parser, config_from_args

    cfg = preset("smoke")
    assert cfg.train.steps_per_superstep == 1  # default: per-step loop
    args = build_parser().parse_args(
        ["--preset", "smoke", "--steps-per-superstep", "4"]
    )
    assert config_from_args(args).train.steps_per_superstep == 4
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--steps-per-superstep", "0"])


def test_trainer_rejects_nonpositive(tmp_path):
    cfg = preset("smoke")
    cfg.data.n_timesteps = 24 * 7 * 2 + 48
    cfg.train.steps_per_superstep = 0
    cfg.train.out_dir = str(tmp_path)
    with pytest.raises(ValueError, match="steps_per_superstep"):
        build_trainer(cfg, verbose=False)


def test_gating_flags(tmp_path):
    """The gate: resident + shared graphs + homogeneous model, S > 1."""
    cfg = preset("smoke")
    cfg.data.n_timesteps = 24 * 7 * 2 + 48
    cfg.train.steps_per_superstep = 4
    cfg.train.data_placement = "resident"
    cfg.train.out_dir = str(tmp_path / "a")
    assert build_trainer(cfg, verbose=False)._superstep_ready()

    # per-city graphs (CitySupports) + heterogeneous cities: falls back
    mc = preset("multicity")
    mc.data.city_rows = (4, 3)
    mc.data.city_timesteps = (24 * 7 * 2 + 24, 24 * 7 * 2)
    mc.mesh.dp = 1
    mc.train.steps_per_superstep = 4
    mc.train.out_dir = str(tmp_path / "b")
    assert not build_trainer(mc, verbose=False)._superstep_ready()
