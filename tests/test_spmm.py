"""Block-sparse Pallas SpMM tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.data import grid_adjacency
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.ops.chebconv import ChebGraphConv, SparseChebGraphConv
from stmgcn_tpu.ops.spmm import BlockSparse, from_dense, spmm, spmm_dense_reference


def banded_matrix(n, w, seed=0):
    rng = np.random.default_rng(seed)
    mat = rng.standard_normal((n, n)).astype(np.float32)
    mat[np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > w] = 0.0
    return mat


class TestFromDense:
    def test_structure(self):
        mat = banded_matrix(256, 10)
        bs = from_dense(mat, tile=128)
        assert bs.block_rows == 2
        assert bs.idx.shape == bs.data.shape[:2]
        assert bs.n == 256

    def test_density_savings_on_grid_supports(self):
        # Sparsity pays when the graph band is small relative to N: a 40x40
        # grid (N=1600, 13 block-rows) with a K=2 Chebyshev band keeps ~3
        # nonzero block-columns per row.
        adj = grid_adjacency(40)
        sup = SupportConfig("chebyshev", 2).build(adj)
        bs = from_dense(sup[2], tile=128)  # T_2: the widest band
        dense_bytes = sup[2].nbytes * 2  # forward + transpose copies
        assert bs.density < 0.5
        assert bs.nbytes < dense_bytes

    def test_non_square_raises(self):
        with pytest.raises(ValueError, match="square"):
            from_dense(np.ones((4, 5)))

    def test_pytree_roundtrip(self):
        bs = from_dense(banded_matrix(128, 5))
        leaves, treedef = jax.tree.flatten(bs)
        bs2 = jax.tree.unflatten(treedef, leaves)
        assert bs2.n == bs.n and bs2.tile == bs.tile


class TestSpmm:
    @pytest.mark.parametrize("n,m,w", [(256, 64, 10), (300, 100, 140), (128, 256, 5)])
    def test_matches_dense(self, n, m, w):
        mat = banded_matrix(n, w)
        x = np.random.default_rng(1).standard_normal((n, m)).astype(np.float32)
        got = spmm(from_dense(mat), jnp.asarray(x))
        want = spmm_dense_reference(mat, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)

    def test_chebyshev_grid_supports_match_dense(self):
        adj = grid_adjacency(18)  # N=324 -> padded 384, 3 block rows
        sups = SupportConfig("chebyshev", 2).build(adj)
        x = np.random.default_rng(2).standard_normal((324, 48)).astype(np.float32)
        for k in range(sups.shape[0]):
            got = spmm(from_dense(sups[k]), jnp.asarray(x))
            want = spmm_dense_reference(sups[k], x)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-4, atol=1e-4)

    def test_gradient_matches_dense(self):
        mat = banded_matrix(256, 20)
        bs = from_dense(mat)
        x = jnp.asarray(np.random.default_rng(3).standard_normal((256, 32)).astype(np.float32))
        c = jnp.asarray(np.random.default_rng(4).standard_normal((256, 32)).astype(np.float32))

        g_sparse = jax.grad(lambda x: jnp.sum(spmm(bs, x) * c))(x)
        g_dense = jax.grad(lambda x: jnp.sum((jnp.asarray(mat) @ x) * c))(x)
        np.testing.assert_allclose(np.asarray(g_sparse), np.asarray(g_dense),
                                   rtol=1e-4, atol=1e-4)

    def test_bf16_gradient_dtype_and_values(self):
        """Single-support path under bf16: the cotangent must come back in
        the primal's dtype (the kernel accumulates f32; _spmm_bwd casts —
        the stack path's twin fix is covered by test_sparse_model.py)."""
        mat = banded_matrix(256, 20)
        bs = from_dense(mat)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((256, 32)), jnp.bfloat16)
        c = jnp.asarray(rng.standard_normal((256, 32)), jnp.bfloat16)

        def loss(x):
            out = spmm(bs, x).astype(x.dtype)  # callers cast fwd output
            return jnp.sum((out * c).astype(jnp.float32))

        g = jax.grad(loss)(x)
        assert g.dtype == jnp.bfloat16
        g_dense = jax.grad(
            lambda x: jnp.sum((jnp.asarray(mat, x.dtype) @ x * c).astype(jnp.float32))
        )(x)
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(g_dense, np.float32),
            rtol=0.05, atol=0.05,
        )

    def test_under_jit_and_value_and_grad(self):
        mat = banded_matrix(128, 6)
        bs = from_dense(mat)
        x = jnp.ones((128, 16), jnp.float32)

        @jax.jit
        def loss(x):
            return jnp.mean(spmm(bs, x) ** 2)

        val, grad = jax.value_and_grad(loss)(x)
        assert np.isfinite(float(val))
        assert np.isfinite(np.asarray(grad)).all()

    def test_shape_validation(self):
        bs = from_dense(banded_matrix(128, 4))
        with pytest.raises(ValueError, match="rows"):
            spmm(bs, jnp.ones((64, 8)))
        with pytest.raises(ValueError, match="\\(N, M\\)"):
            spmm(bs, jnp.ones((128,)))


class TestBlockSparseStack:
    """Fused K-support single-launch kernel (spmm_stack)."""

    def make(self, K=3, n=300, m=70, w=40, seed=0):
        from stmgcn_tpu.ops.spmm import stack_from_dense

        rng = np.random.default_rng(seed)
        mats = rng.standard_normal((K, n, n)).astype(np.float32)
        dist = np.abs(np.subtract.outer(np.arange(n), np.arange(n)))
        mats[:, dist > w] = 0.0
        x = rng.standard_normal((n, m)).astype(np.float32)
        return mats, x, stack_from_dense(mats)

    def test_matches_dense_all_k(self):
        from stmgcn_tpu.ops.spmm import spmm_stack

        mats, x, bss = self.make()
        got = spmm_stack(bss, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(got), np.einsum("kij,jm->kim", mats, x), rtol=1e-4, atol=1e-4
        )

    def test_gradient_matches_dense(self):
        from stmgcn_tpu.ops.spmm import spmm_stack

        mats, x, bss = self.make()
        c = np.random.default_rng(9).standard_normal((3, 300, 70)).astype(np.float32)
        g = jax.grad(lambda xx: jnp.sum(spmm_stack(bss, xx) * jnp.asarray(c)))(
            jnp.asarray(x)
        )
        np.testing.assert_allclose(
            np.asarray(g), np.einsum("kij,kim->jm", mats, c), rtol=1e-4, atol=1e-4
        )

    def test_rectangular_strip(self):
        from stmgcn_tpu.ops.spmm import spmm_stack, stack_from_dense

        mats, x, _ = self.make()
        strip = mats[:, 100:200, :]  # (K, 100, 300) row strip
        got = spmm_stack(stack_from_dense(strip), jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(got), np.einsum("kij,jm->kim", strip, x), rtol=1e-4, atol=1e-4
        )

    def test_matches_per_support_loop(self):
        from stmgcn_tpu.ops.spmm import from_dense, spmm, spmm_stack

        mats, x, bss = self.make(K=2, n=256, w=12)
        fused = spmm_stack(bss, jnp.asarray(x))
        for k in range(2):
            loop = spmm(from_dense(mats[k]), jnp.asarray(x))
            np.testing.assert_allclose(
                np.asarray(fused[k]), np.asarray(loop), rtol=1e-5, atol=1e-5
            )

    def test_shape_validation(self):
        from stmgcn_tpu.ops.spmm import spmm_stack, stack_from_dense

        _, _, bss = self.make(n=256)
        with pytest.raises(ValueError, match="rows"):
            spmm_stack(bss, jnp.ones((128, 8)))
        with pytest.raises(ValueError, match="\\(K, Nr, Nc\\)"):
            stack_from_dense(np.ones((4, 5)))


class TestSparseChebGraphConv:
    def test_matches_dense_layer_with_same_params(self):
        adj = grid_adjacency(12)  # N=144
        sups = SupportConfig("chebyshev", 2).build(adj)
        x = jnp.asarray(
            np.random.default_rng(5).standard_normal((4, 144, 6)).astype(np.float32)
        )
        dense_layer = ChebGraphConv(n_supports=3, features=8)
        params = dense_layer.init(jax.random.key(0), jnp.asarray(sups), x)
        want = dense_layer.apply(params, jnp.asarray(sups), x)

        sparse_layer = SparseChebGraphConv(n_supports=3, features=8)
        bs_list = tuple(from_dense(sups[k]) for k in range(3))
        got = sparse_layer.apply(params, bs_list, x)  # identical param tree
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)

    def test_support_count_mismatch(self):
        bs = (from_dense(banded_matrix(128, 4)),)
        layer = SparseChebGraphConv(n_supports=2, features=4)
        with pytest.raises(ValueError, match="supports"):
            layer.init(jax.random.key(0), bs, jnp.ones((2, 128, 3)))
