"""Native C++ host-kernel tests: build, parity with numpy, fallback."""

import shutil

import numpy as np
import pytest

from stmgcn_tpu import native
from stmgcn_tpu.data import WindowSpec, sliding_windows

needs_toolchain = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="no C++ toolchain",
)


@needs_toolchain
class TestNative:
    def test_builds_and_loads(self):
        assert native.available()

    def test_window_gather_matches_numpy(self):
        rng = np.random.default_rng(0)
        data = rng.standard_normal((24 * 8, 7, 2)).astype(np.float32)
        spec = WindowSpec(3, 1, 0, 24)
        x_native, y_native = native.window_gather(data, spec.offsets, spec.burn_in)
        targets = np.arange(spec.burn_in, data.shape[0])
        x_np = data[targets[:, None] + spec.offsets[None, :]]
        y_np = data[targets]
        np.testing.assert_array_equal(x_native, x_np)
        np.testing.assert_array_equal(y_native, y_np)

    def test_sliding_windows_uses_native_transparently(self):
        # same public call, float32 3-D input -> native path; result must be
        # bit-identical to the numpy gather (covered above); sanity here
        data = np.random.default_rng(1).standard_normal((24 * 8, 5, 1)).astype(np.float32)
        spec = WindowSpec(2, 1, 0, 24)
        x, y = sliding_windows(data, spec)
        assert x.shape == (data.shape[0] - spec.burn_in, spec.seq_len, 5, 1)
        np.testing.assert_array_equal(x[:, -1], data[spec.burn_in - 1 : -1])

    def test_nonzero_block_scan_matches_numpy(self):
        rng = np.random.default_rng(2)
        n_pad, tile = 512, 128
        mat = np.zeros((n_pad, n_pad), dtype=np.float32)
        # scatter some nonzeros, including one at a block edge
        for i, j in [(0, 0), (127, 127), (128, 0), (300, 470), (511, 384)]:
            mat[i, j] = rng.standard_normal()
        got = native.nonzero_block_scan(mat, tile)
        r = n_pad // tile
        want = np.any(
            mat.reshape(r, tile, r, tile).transpose(0, 2, 1, 3) != 0, axis=(2, 3)
        )
        np.testing.assert_array_equal(got, want)

    def test_spmm_from_dense_unchanged_by_native_path(self):
        from stmgcn_tpu.ops.spmm import from_dense

        rng = np.random.default_rng(3)
        mat = rng.standard_normal((256, 256)).astype(np.float32)
        mat[np.abs(np.subtract.outer(np.arange(256), np.arange(256))) > 9] = 0
        bs = from_dense(mat)
        # reconstruct the dense matrix from the block structure
        r, c_max = bs.idx.shape
        tile = bs.tile
        recon = np.zeros((r * tile, r * tile), dtype=np.float32)
        data = np.asarray(bs.data)
        idx = np.asarray(bs.idx)
        for i in range(r):
            for c in range(c_max):
                recon[i * tile : (i + 1) * tile,
                      idx[i, c] * tile : (idx[i, c] + 1) * tile] += data[i, c]
        np.testing.assert_array_equal(recon[:256, :256], mat)


class TestFallback:
    def test_disabled_via_env(self, monkeypatch):
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
        assert not native.available()
        assert native.window_gather(np.zeros((10, 2, 1), np.float32),
                                    np.array([-1]), 1) is None
        # public API still works through the numpy fallback
        data = np.random.default_rng(4).standard_normal((30, 3, 1)).astype(np.float32)
        x, y = sliding_windows(data, WindowSpec(2, 0, 0, 24))
        assert x.shape == (28, 2, 3, 1)
