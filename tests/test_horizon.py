"""Multi-step (seq2seq) forecast horizon tests (BASELINE config 5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.data import DemandDataset, WindowSpec, sliding_windows, synthetic_dataset
from stmgcn_tpu.experiment import build_trainer
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.train import make_optimizer, make_step_fns


class TestHorizonWindowing:
    def test_multi_step_targets(self):
        data = np.arange(40, dtype=np.float32).reshape(40, 1, 1)
        spec = WindowSpec(3, 0, 0, 24, horizon=4)
        x, y = sliding_windows(data, spec)
        assert x.shape == (40 - 3 - 3, 3, 1, 1)
        assert y.shape == (34, 4, 1, 1)
        # sample 0: history [0,1,2], targets [3,4,5,6]
        np.testing.assert_array_equal(x[0, :, 0, 0], [0, 1, 2])
        np.testing.assert_array_equal(y[0, :, 0, 0], [3, 4, 5, 6])
        # last sample's final target is the last timestep
        assert y[-1, -1, 0, 0] == 39

    def test_horizon_one_backward_compatible(self):
        data = np.random.default_rng(0).standard_normal((40, 3, 1)).astype(np.float32)
        x1, y1 = sliding_windows(data, WindowSpec(3, 0, 0, 24, horizon=1))
        assert y1.ndim == 3  # no horizon axis

    def test_bad_horizon(self):
        with pytest.raises(ValueError, match="horizon"):
            WindowSpec(3, 0, 0, 24, horizon=0)

    def test_too_short_for_horizon(self):
        with pytest.raises(ValueError, match="horizon"):
            sliding_windows(np.zeros((5, 2, 1)), WindowSpec(3, 0, 0, 24, horizon=3))


class TestHorizonModel:
    def test_output_shape_and_grad(self):
        rng = np.random.default_rng(0)
        sup = jnp.asarray(rng.standard_normal((2, 3, 6, 6)).astype(np.float32) * 0.2)
        x = jnp.asarray(rng.standard_normal((4, 5, 6, 1)).astype(np.float32))
        y = jnp.asarray(rng.standard_normal((4, 8, 6, 1)).astype(np.float32))
        model = STMGCN(m_graphs=2, n_supports=3, seq_len=5, input_dim=1, horizon=8,
                       lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
        fns = make_step_fns(model, make_optimizer(1e-2), "mse")
        params, opt_state = fns.init(jax.random.key(0), sup, x)
        out = model.apply(params, sup, x)
        assert out.shape == (4, 8, 6, 1)
        first = None
        for _ in range(10):
            params, opt_state, loss = fns.train_step(
                params, opt_state, sup, x, y, jnp.ones(4)
            )
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_masked_loss_matches_ragged_4d(self):
        rng = np.random.default_rng(1)
        sup = jnp.asarray(rng.standard_normal((2, 3, 6, 6)).astype(np.float32) * 0.2)
        x = jnp.asarray(rng.standard_normal((6, 5, 6, 1)).astype(np.float32))
        y = jnp.asarray(rng.standard_normal((6, 4, 6, 1)).astype(np.float32))
        model = STMGCN(m_graphs=2, n_supports=3, seq_len=5, input_dim=1, horizon=4,
                       lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
        fns = make_step_fns(model, make_optimizer(1e-3), "mse")
        params, _ = fns.init(jax.random.key(0), sup, x)
        mask = jnp.asarray((np.arange(6) < 4).astype(np.float32))
        lm, _ = fns.eval_step(params, sup, x, y, mask)
        lr, _ = fns.eval_step(params, sup, x[:4], y[:4], jnp.ones(4))
        np.testing.assert_allclose(float(lm), float(lr), rtol=1e-6)


class TestHorizonOnMesh:
    def test_4d_targets_shard_node_axis(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from stmgcn_tpu.parallel import MeshPlacement, build_mesh

        pl_ = MeshPlacement(build_mesh(dp=2, region=4))
        # H=4 NOT divisible by region=4's partner dim check — node axis must
        # carry 'region', horizon axis must stay unsharded
        y = np.zeros((8, 4, 16, 1), np.float32)
        placed = pl_.put(y, "y")
        assert placed.addressable_shards[0].data.shape == (4, 4, 4, 1)
        # 3-D y keeps the original spec
        y3 = np.zeros((8, 16, 1), np.float32)
        placed3 = pl_.put(y3, "y")
        assert placed3.addressable_shards[0].data.shape == (4, 4, 1)

    def test_sharded_train_step_with_horizon(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from stmgcn_tpu.parallel import MeshPlacement, build_mesh

        rng = np.random.default_rng(2)
        sup = (rng.standard_normal((2, 3, 16, 16)) * 0.2).astype(np.float32)
        x = rng.standard_normal((8, 5, 16, 1)).astype(np.float32)
        y = rng.standard_normal((8, 6, 16, 1)).astype(np.float32)
        model = STMGCN(m_graphs=2, n_supports=3, seq_len=5, input_dim=1, horizon=6,
                       lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8)
        fns = make_step_fns(model, make_optimizer(1e-3), "mse")
        params, opt = fns.init(jax.random.key(0), jnp.asarray(sup), jnp.asarray(x))
        loss_single, _ = fns.eval_step(params, jnp.asarray(sup), jnp.asarray(x),
                                       jnp.asarray(y), jnp.ones(8))
        pl_ = MeshPlacement(build_mesh(dp=2, region=4))
        loss_mesh, _ = fns.eval_step(
            pl_.put(params, "state"), pl_.put(sup, "supports"), pl_.put(x, "x"),
            pl_.put(y, "y"), pl_.put(np.ones(8, np.float32), "mask"),
        )
        np.testing.assert_allclose(float(loss_mesh), float(loss_single), rtol=1e-5)

    @pytest.mark.slow
    def test_longhorizon_trains_on_banded_mesh_with_padding(self, tmp_path):
        """Seq2seq (4-D targets) x banded routing x node padding compose:
        the longhorizon preset on a (dp=4, region=2) mesh at N=25 -> 26."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 virtual devices")
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.experiment import build_trainer

        cfg = preset("longhorizon")
        cfg.data.rows = 5  # N=25: pads to 26 over region=2 (13-node shards)
        cfg.data.serial_len = 6
        cfg.data.horizon = 4
        cfg.data.n_timesteps = 24 * 7 * 2 + 48
        cfg.train.epochs = 1
        cfg.train.batch_size = 16
        cfg.train.out_dir = str(tmp_path)
        cfg.mesh.dp, cfg.mesh.region = 4, 2
        cfg.mesh.region_strategy = "auto"
        cfg.mesh.halo = 10  # grid bandwidth 2*5=10 <= shard 13 -> banded
        trainer = build_trainer(cfg, verbose=False)
        assert trainer.node_pad == 1
        assert "banded" in trainer.model.branch_modes()
        hist = trainer.train()
        assert np.isfinite(hist["train"]).all()
        res = trainer.test(modes=("test",))
        assert np.isfinite(res["test"]["rmse"])


class TestLongHorizonPreset:
    def test_end_to_end(self, tmp_path):
        cfg = preset("longhorizon")
        cfg.data.rows = 3
        cfg.data.n_timesteps = 24 * 7 * 2 + 100
        cfg.train.epochs = 1
        cfg.train.batch_size = 16
        cfg.train.out_dir = str(tmp_path)
        trainer = build_trainer(cfg, verbose=False)
        hist = trainer.train()
        assert np.isfinite(hist["train"][0])
        res = trainer.test(modes=("test",))
        assert np.isfinite(res["test"]["rmse"])
