"""The static-analysis subsystem's own tests (stmgcn_tpu.analysis).

Three layers: (1) every AST rule fires on a known-bad fixture and stays
quiet on the matching known-good twin; (2) the contract pass flags
synthetic jaxpr violations and passes the real smoke-preset steps;
(3) the shipped package is clean — the tier-1 gate that turns every
future hazard of this class into a test failure instead of a latent TPU
incident.
"""

import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.analysis import (
    RULES,
    check_partition_specs,
    check_step_contracts,
    lint_package,
    lint_source,
)
from stmgcn_tpu.analysis.jaxpr_check import _check_one, count_primitives
from stmgcn_tpu.analysis.report import render_json


def _rules(findings):
    return {f.rule for f in findings}


def lint(src):
    return lint_source(textwrap.dedent(src), "fixture.py")


class TestCompatImportRule:
    def test_from_jax_import_shard_map(self):
        f = lint("from jax import shard_map\n")
        assert _rules(f) == {"jax-compat-import"}
        assert "0.5.x" in f[0].message

    def test_experimental_shard_map(self):
        f = lint("from jax.experimental.shard_map import shard_map\n")
        assert _rules(f) == {"jax-compat-import"}

    def test_import_module_form(self):
        f = lint("import jax.experimental.maps\n")
        assert _rules(f) == {"jax-compat-import"}

    def test_versioned_attr_call(self):
        f = lint(
            """
            import jax

            def f(x):
                return jax.lax.axis_size("region") * x
            """
        )
        assert _rules(f) == {"jax-compat-import"}

    def test_aliased_attr_call_resolves(self):
        # `import jax as j; j.tree_map(...)` must still resolve
        f = lint("import jax as j\nout = j.tree_map(abs, {})\n")
        assert _rules(f) == {"jax-compat-import"}

    def test_shim_import_is_clean(self):
        f = lint("from stmgcn_tpu.utils.platform import shard_map\n")
        assert f == []


class TestHostSyncRule:
    def test_item_in_jitted_function(self):
        f = lint(
            """
            import jax

            @jax.jit
            def step(x):
                return x.sum().item()
            """
        )
        assert _rules(f) == {"host-sync-in-jit"}

    def test_transitive_reachability(self):
        f = lint(
            """
            import jax

            @jax.jit
            def step(x):
                return helper(x)

            def helper(x):
                return float(x.sum())
            """
        )
        assert _rules(f) == {"host-sync-in-jit"}

    @pytest.mark.parametrize(
        "stmt",
        [
            "jax.device_get(x)",
            "x.block_until_ready()",
            "np.asarray(x)",
        ],
    )
    def test_each_sync_call(self, stmt):
        f = lint(
            f"""
            import jax
            import numpy as np

            @jax.jit
            def step(x):
                return {stmt}
            """
        )
        assert _rules(f) == {"host-sync-in-jit"}

    def test_host_code_not_flagged(self):
        # same calls outside any jit-reachable function: clean
        f = lint(
            """
            import numpy as np

            def metrics(pred, true):
                return float(np.mean(np.square(np.asarray(pred) - true)))
            """
        )
        assert f == []

    def test_flax_module_method_is_reachable(self):
        f = lint(
            """
            from flax import linen as nn

            class Model(nn.Module):
                def __call__(self, x):
                    return x.sum().item()
            """
        )
        assert _rules(f) == {"host-sync-in-jit"}

    def test_float_of_literal_ok(self):
        f = lint(
            """
            import jax

            @jax.jit
            def step(x):
                return x * float("inf")
            """
        )
        assert f == []


class TestTracedControlFlowRule:
    def test_if_on_jnp_value(self):
        f = lint(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def step(x):
                if jnp.any(x > 0):
                    return x
                return -x
            """
        )
        assert _rules(f) == {"traced-control-flow"}

    def test_while_on_method_any(self):
        f = lint(
            """
            import jax

            @jax.jit
            def step(x):
                while (x > 0).all():
                    x = x - 1
                return x
            """
        )
        assert _rules(f) == {"traced-control-flow"}

    def test_static_shape_branching_ok(self):
        f = lint(
            """
            import jax

            @jax.jit
            def step(x):
                if x.ndim == 1:
                    return x
                return x[0]
            """
        )
        assert f == []


class TestUnfencedTimingRule:
    BAD = """
        import time

        def bench(train_step, batches):
            t0 = time.perf_counter()
            for b in batches:
                out = train_step(b)
            return time.perf_counter() - t0
        """

    def test_span_without_fence(self):
        f = lint(self.BAD)
        assert _rules(f) == {"unfenced-timing"}
        assert all(x.severity == "warning" for x in f)

    def test_span_with_fence_ok(self):
        f = lint(
            """
            import time

            def bench(train_step, batches, fence):
                t0 = time.perf_counter()
                for b in batches:
                    out = train_step(b)
                fence(out)
                return time.perf_counter() - t0
            """
        )
        assert f == []

    def test_span_without_dispatch_ok(self):
        f = lint(
            """
            import time

            def wall(load, path):
                t0 = time.time()
                data = load(path)
                return data, time.time() - t0
            """
        )
        assert f == []


class TestMissingDonateRule:
    def test_call_form(self):
        f = lint(
            """
            import jax

            def train_step(params, opt_state, batch):
                return params, opt_state

            fn = jax.jit(train_step)
            """
        )
        assert _rules(f) == {"missing-donate"}

    def test_decorator_form(self):
        f = lint(
            """
            import jax

            @jax.jit
            def train_step(params, opt_state, batch):
                return params, opt_state
            """
        )
        assert "missing-donate" in _rules(f)

    def test_donated_ok(self):
        f = lint(
            """
            import jax

            def train_step(params, opt_state, batch):
                return params, opt_state

            fn = jax.jit(train_step, donate_argnums=(0, 1))
            """
        )
        assert f == []


class TestRecompileHazardRule:
    def test_jit_invoked_in_place(self):
        f = lint(
            """
            import jax

            def hot(x):
                return jax.jit(lambda v: v + 1)(x)
            """
        )
        assert _rules(f) == {"recompile-hazard"}
        assert all(x.severity == "warning" for x in f)

    def test_lambda_at_static_argnum(self):
        f = lint(
            """
            import jax

            def f(x, act):
                return act(x)

            g = jax.jit(f, static_argnums=(1,))

            def use(x):
                return g(x, lambda v: v * 2)
            """
        )
        assert _rules(f) == {"recompile-hazard"}
        assert "position 1" in f[0].message

    def test_dict_at_static_argname(self):
        f = lint(
            """
            import jax

            def f(x, cfg=None):
                return x

            g = jax.jit(f, static_argnames=("cfg",))

            def use(x):
                return g(x, cfg={"k": 1})
            """
        )
        assert _rules(f) == {"recompile-hazard"}
        assert "unhashable" in f[0].message

    def test_factory_and_module_binding_ok(self):
        # the two blessed shapes: a factory returning the bound wrapper
        # (make_step_fns) and a module-scope jit-of-lambda bound once
        f = lint(
            """
            import jax

            def make(f):
                return jax.jit(f, donate_argnums=(0, 1))

            g = jax.jit(lambda v: v + 1)

            def use(x):
                return g(x)
            """
        )
        assert f == []

    def test_hashable_static_value_ok(self):
        f = lint(
            """
            import jax

            def f(x, k):
                return x * k

            g = jax.jit(f, static_argnums=(1,))

            def use(x):
                return g(x, 3)
            """
        )
        assert f == []


class TestSuppression:
    def test_rule_specific(self):
        f = lint("from jax import shard_map  # stmgcn: ignore[jax-compat-import]\n")
        assert f == []

    def test_bare_ignore(self):
        f = lint("from jax import shard_map  # stmgcn: ignore\n")
        assert f == []

    def test_wrong_rule_does_not_suppress(self):
        f = lint("from jax import shard_map  # stmgcn: ignore[missing-donate]\n")
        assert _rules(f) == {"jax-compat-import"}

    def test_other_lines_unaffected(self):
        f = lint(
            "from jax import shard_map  # stmgcn: ignore\n"
            "from jax import linear_util\n"
        )
        assert len(f) == 1 and f[0].line == 2


class TestContractChecks:
    def test_primitive_budget_fires(self):
        jaxpr = jax.make_jaxpr(lambda x: jnp.sin(jnp.cos(x)) + x)(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        )
        assert count_primitives(jaxpr) >= 3
        f = _check_one("toy", jaxpr, True, budget=1)
        assert _rules(f) == {"primitive-budget"}

    def test_weak_type_output_fires(self):
        # exp of a python scalar stays weak — feeding it back recompiles
        jaxpr = jax.make_jaxpr(lambda x: jnp.exp(2.0))(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        )
        f = _check_one("toy", jaxpr, True, budget=None)
        assert _rules(f) == {"weak-type-output"}

    def test_fp64_promotion_fires(self):
        from jax.experimental import enable_x64

        with enable_x64():
            jaxpr = jax.make_jaxpr(lambda x: x.astype(jnp.float64))(
                jax.ShapeDtypeStruct((4,), jnp.float32)
            )
        f = _check_one("toy", jaxpr, True, budget=None)
        assert "fp64-promotion" in _rules(f)

    def test_smoke_steps_pass(self):
        assert check_step_contracts("smoke") == []

    def test_superstep_program_within_budget(self):
        """The fused S-step scan is a checked program with its own budget
        (satellite of the superstep PR): present, measured, and under."""
        from stmgcn_tpu.analysis.jaxpr_check import (
            PRIMITIVE_BUDGETS,
            measured_primitive_counts,
        )

        assert "train_superstep" in PRIMITIVE_BUDGETS
        counts = measured_primitive_counts("smoke")
        assert set(counts) == set(PRIMITIVE_BUDGETS)
        for name, count in counts.items():
            assert 0 < count <= PRIMITIVE_BUDGETS[name], name


class TestRebaseline:
    def test_rewrites_literal_and_reports(self, tmp_path):
        """--rebaseline against a copy: the single-line literal is
        rewritten to measured x headroom (rounded up to 10s) and the
        returned budgets round-trip through the rewritten source."""
        import math

        import stmgcn_tpu.analysis.jaxpr_check as jc

        target = tmp_path / "jaxpr_check_copy.py"
        target.write_text(open(jc.__file__).read())
        before = dict(jc.PRIMITIVE_BUDGETS)
        try:
            result = jc.rebaseline(path=str(target), headroom=3.0)
            assert result["path"] == str(target)
            assert result["budgets"] == {
                name: int(math.ceil(c * 3.0 / 10.0) * 10)
                for name, c in result["counts"].items()
            }
            # in-memory budgets updated so later contract checks see them
            assert jc.PRIMITIVE_BUDGETS == result["budgets"]
            line = next(
                l for l in target.read_text().splitlines()
                if l.startswith("PRIMITIVE_BUDGETS = ")
            )
            ns = {}
            exec(line, ns)
            assert ns["PRIMITIVE_BUDGETS"] == result["budgets"]
        finally:
            jc.PRIMITIVE_BUDGETS.clear()
            jc.PRIMITIVE_BUDGETS.update(before)

    def test_rejects_shrinking_headroom(self):
        from stmgcn_tpu.analysis.jaxpr_check import rebaseline

        with pytest.raises(ValueError, match="headroom"):
            rebaseline(headroom=0.5)

    def test_missing_literal_raises(self, tmp_path):
        import stmgcn_tpu.analysis.jaxpr_check as jc

        target = tmp_path / "no_literal.py"
        target.write_text("x = 1\n")
        before = dict(jc.PRIMITIVE_BUDGETS)
        try:
            with pytest.raises(RuntimeError, match="PRIMITIVE_BUDGETS"):
                jc.rebaseline(path=str(target))
        finally:
            jc.PRIMITIVE_BUDGETS.clear()
            jc.PRIMITIVE_BUDGETS.update(before)


class TestShardingChecks:
    def test_bad_axis_name_fires(self, tmp_path):
        (tmp_path / "bad.py").write_text(
            "from jax.sharding import PartitionSpec as P\n"
            'SPEC = P("dp", "regoin", None)\n'
        )
        f = check_partition_specs(str(tmp_path))
        assert any(
            x.rule == "partition-axis-name" and "regoin" in x.message for x in f
        )

    def test_variable_axis_names_skipped(self, tmp_path):
        (tmp_path / "ok.py").write_text(
            "from jax.sharding import PartitionSpec as P\n"
            "def f(ax):\n"
            "    return P(ax, None)\n"
        )
        f = check_partition_specs(str(tmp_path))
        assert not [x for x in f if x.path.endswith("ok.py")]

    def test_repo_placement_table_clean(self):
        assert check_partition_specs() == []


class TestShippedTreeClean:
    def test_package_lints_clean(self):
        findings = lint_package()
        assert findings == [], "\n".join(str(f) for f in findings)


class TestCli:
    def test_lint_subcommand_clean_exit(self):
        from stmgcn_tpu.cli import main

        assert main(["lint", "--no-contracts"]) == 0

    def test_json_gate_on_fixture(self, tmp_path, capsys):
        (tmp_path / "bad.py").write_text(
            "from jax import shard_map\n"
            "import jax\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return x.sum().item()\n"
        )
        from stmgcn_tpu.cli import main

        rc = main(["lint", str(tmp_path), "--format", "json"])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["errors"] == 2
        assert {f["rule"] for f in payload["findings"]} == {
            "jax-compat-import",
            "host-sync-in-jit",
        }

    def test_list_rules(self, capsys):
        from stmgcn_tpu.cli import main

        assert main(["lint", "--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in RULES:
            assert rule in out


class TestReport:
    def test_json_shape_stable(self):
        payload = json.loads(render_json([]))
        assert payload == {
            "version": 3, "errors": 0, "warnings": 0, "findings": [],
        }

    def test_finding_records_carry_chain_and_suppressed(self):
        from stmgcn_tpu.analysis import Finding

        f = Finding(rule="r", path="p.py", line=1, message="m",
                    chain=("a:f", "b:g"), suppressed=True)
        rec = json.loads(render_json([f]))["findings"][0]
        assert rec["chain"] == ["a:f", "b:g"]
        assert rec["suppressed"] is True
        assert "[via a:f -> b:g]" in str(f) and "(suppressed)" in str(f)

    def test_findings_sorted_by_location(self):
        from stmgcn_tpu.analysis import Finding

        fs = [
            Finding(rule="b", path="z.py", line=9, message="m"),
            Finding(rule="a", path="a.py", line=3, message="m"),
        ]
        payload = json.loads(render_json(fs))
        assert [f["path"] for f in payload["findings"]] == ["a.py", "z.py"]


class TestCompatShim:
    """The satellite the linter motivates: the version-portable symbols."""

    def test_shard_map_round_trip(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from stmgcn_tpu.utils.platform import shard_map

        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs >= 2 virtual devices")
        mesh = Mesh(np.array(devs[:2]), ("region",))
        out = shard_map(
            lambda v: v * 2,
            mesh=mesh,
            in_specs=P("region"),
            out_specs=P("region"),
        )(jnp.arange(4.0))
        np.testing.assert_allclose(np.asarray(out), np.arange(4.0) * 2)

    def test_axis_size_is_static(self):
        from jax.sharding import Mesh, PartitionSpec as P

        from stmgcn_tpu.utils.platform import axis_size, shard_map

        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs >= 2 virtual devices")
        mesh = Mesh(np.array(devs[:2]), ("region",))
        sizes = []

        def f(v):
            n = axis_size("region")
            sizes.append(n)
            return v + n

        out = shard_map(
            f, mesh=mesh, in_specs=P("region"), out_specs=P("region")
        )(jnp.zeros(2))
        np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
        # range()-compatible: the halo exchange builds ppermute tables
        assert all(isinstance(int(s), int) for s in sizes)


class TestCollectiveChecks:
    """collective-shape: static mesh-vs-operand math for every preset."""

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_collective_contracts

        assert check_collective_contracts() == []

    def test_scaled_preset_math_is_the_documented_margin(self):
        """The scaled preset sits 6 rows inside the halo budget (bandwidth
        150 vs budget 156 at shard size 313) — the check must know that."""
        from stmgcn_tpu.analysis.collective_check import grid_bandwidth_estimate
        from stmgcn_tpu.config import preset

        cfg = preset("scaled")
        padded = -(-50 * 50 // cfg.mesh.region) * cfg.mesh.region
        n_local = padded // cfg.mesh.region
        assert (padded, n_local) == (2504, 313)
        assert grid_bandwidth_estimate(cfg.model.kernel_type, cfg.model.K, 50) == 150
        assert 150 <= n_local // 2 == 156

    def test_ragged_dp_batch_fires(self):
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.config import preset

        bad = preset("multicity")
        bad.train.batch_size = 30
        f = check_collective_contracts([("bad", bad)])
        assert [x.rule for x in f] == ["collective-shape"]
        assert f[0].severity == "error" and "dp=8" in f[0].message

    def test_branch_psum_raggedness_fires(self):
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.config import preset

        bad = preset("default")
        bad.mesh.branch = 2  # m_graphs=3
        f = check_collective_contracts([("bad", bad)])
        assert any("m_graphs" in x.message for x in f)

    def test_halo_exceeding_shard_fires(self):
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.config import preset

        bad = preset("scaled")
        bad.mesh.halo = 999
        f = check_collective_contracts([("bad", bad)])
        assert any("ppermute" in x.message for x in f)

    def test_banded_over_budget_and_oversharded_grid_fire(self):
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.config import preset

        bad = preset("scaled")
        bad.mesh.region_strategy = "banded"
        bad.mesh.halo = 100  # < bandwidth 150
        f = check_collective_contracts([("bad", bad)])
        assert any("halo budget 100" in x.message for x in f)

        bad = preset("scaled")
        bad.mesh.region = 64  # shard size 40 < bandwidth 150: no halo fits
        f = check_collective_contracts([("bad", bad)])
        assert any("exceeds the shard size 40" in x.message for x in f)

    def test_single_device_configs_skipped(self):
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.config import preset

        cfg = preset("smoke")
        cfg.train.batch_size = 31  # would be ragged on any dp mesh
        assert check_collective_contracts([("smoke", cfg)]) == []


class TestServingBucketRule:
    """Pass 2e: the serving-bucket-shape ladder contract (pure config
    math — the same violations() the engine enforces at construction,
    surfaced at lint time instead of deploy time)."""

    def test_rule_registered_as_error(self):
        assert RULES["serving-bucket-shape"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_serving_buckets

        assert check_serving_buckets() == []

    def test_flags_non_increasing_ladder(self):
        from stmgcn_tpu.analysis import check_serving_buckets
        from stmgcn_tpu.config import ServingConfig, preset

        bad = preset("smoke")
        bad.serving = ServingConfig(buckets=(4, 2, 1), max_batch=4)
        f = check_serving_buckets([("bad", bad)])
        assert f and all(x.rule == "serving-bucket-shape" for x in f)
        assert all(x.severity == "error" for x in f)
        assert any("strictly increasing" in x.message for x in f)
        assert f[0].path == "<contract:serving:bad>"

    def test_flags_ladder_below_max_batch(self):
        from stmgcn_tpu.analysis import check_serving_buckets
        from stmgcn_tpu.config import ServingConfig, preset

        bad = preset("smoke")
        bad.serving = ServingConfig(buckets=(1, 4, 16), max_batch=64)
        f = check_serving_buckets([("bad", bad)])
        assert any("max_batch" in x.message for x in f)

    def test_flags_excessive_pad_waste(self):
        from stmgcn_tpu.analysis import check_serving_buckets
        from stmgcn_tpu.config import ServingConfig, preset

        bad = preset("smoke")
        # one row past rung 1 pads 14 of 16 rows: waste 0.875 > 0.5
        bad.serving = ServingConfig(
            buckets=(1, 16), max_batch=16, max_pad_waste=0.5
        )
        f = check_serving_buckets([("bad", bad)])
        assert any("pad waste" in x.message for x in f)

    def test_configs_without_serving_section_skipped(self):
        from stmgcn_tpu.analysis import check_serving_buckets

        assert check_serving_buckets([("none", object())]) == []


class TestServingSLORule:
    """Pass 2f: the serving-slo admission contract — SLO knob combinations
    that construct an admission controller that can never behave as
    intended, caught from pure config math at lint time. The boundaries
    are pinned exactly: one unit past each threshold must go clean."""

    @staticmethod
    def _cfg(**kw):
        from stmgcn_tpu.config import ServingConfig, preset

        base = dict(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0)
        base.update(kw)
        cfg = preset("smoke")
        cfg.serving = ServingConfig(**base)
        return cfg

    def test_rule_registered_as_error(self):
        assert RULES["serving-slo"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_serving_slo

        assert check_serving_slo() == []

    def test_deadline_at_coalescing_floor_flagged(self):
        from stmgcn_tpu.analysis import check_serving_slo

        f = check_serving_slo([("bad", self._cfg(deadline_ms=5.0))])
        assert f and all(x.rule == "serving-slo" for x in f)
        assert all(x.severity == "error" for x in f)
        assert any("max_delay_ms" in x.message for x in f)
        assert f[0].path == "<contract:serving:bad>"
        # one epsilon above the floor is servable
        assert check_serving_slo([("ok", self._cfg(deadline_ms=5.1))]) == []

    def test_queue_bound_below_top_rung_flagged(self):
        from stmgcn_tpu.analysis import check_serving_slo

        f = check_serving_slo([("bad", self._cfg(queue_bound_rows=3))])
        assert any("top" in x.message and "rung" in x.message for x in f)
        # exactly the top rung can fill one saturated dispatch: clean
        assert check_serving_slo([("ok", self._cfg(queue_bound_rows=4))]) == []
        # zero means unbounded, not "a bound of zero": clean
        assert check_serving_slo([("ok", self._cfg(queue_bound_rows=0))]) == []

    def test_degrade_rung_misconfigurations_flagged(self):
        from stmgcn_tpu.analysis import check_serving_slo

        off_ladder = self._cfg(shed_policy="degrade", degrade_rung=3)
        f = check_serving_slo([("bad", off_ladder)])
        assert any("not a ladder rung" in x.message for x in f)
        unused = self._cfg(shed_policy="reject", degrade_rung=2)
        f = check_serving_slo([("bad", unused)])
        assert any("never be used" in x.message for x in f)
        assert check_serving_slo(
            [("ok", self._cfg(shed_policy="degrade", degrade_rung=2))]
        ) == []

    def test_bad_shed_policy_flagged(self):
        from stmgcn_tpu.analysis import check_serving_slo

        f = check_serving_slo([("bad", self._cfg(shed_policy="retry"))])
        assert any("shed_policy" in x.message for x in f)

    def test_configs_without_serving_section_skipped(self):
        from stmgcn_tpu.analysis import check_serving_slo

        assert check_serving_slo([("none", object())]) == []


class TestObsOverheadRule:
    """Pass 2h: the obs-overhead budget contract — observability knobs
    that would make the measurement layer a memory regression of its
    own. Boundaries pinned exactly: the documented budget itself is
    clean, one past it is flagged; ring bounds apply only once tracing
    actually allocates a ring."""

    @staticmethod
    def _cfg(**kw):
        from stmgcn_tpu.config import ObsConfig, preset

        cfg = preset("smoke")
        cfg.obs = ObsConfig(**kw)
        return cfg

    def test_rule_registered_as_error(self):
        assert RULES["obs-overhead"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_obs_overhead

        assert check_obs_overhead() == []

    def test_reservoir_budget_boundary(self):
        from stmgcn_tpu.analysis import check_obs_overhead
        from stmgcn_tpu.config import OBS_RESERVOIR_BUDGET

        # reservoir bounds apply even with tracing OFF — EngineStats
        # histograms exist in every serving process
        f = check_obs_overhead(
            [("bad", self._cfg(reservoir=OBS_RESERVOIR_BUDGET + 1))]
        )
        assert f and all(x.rule == "obs-overhead" for x in f)
        assert all(x.severity == "error" for x in f)
        assert any("budget" in x.message for x in f)
        assert f[0].path == "<contract:obs:bad>"
        assert check_obs_overhead(
            [("ok", self._cfg(reservoir=OBS_RESERVOIR_BUDGET))]
        ) == []

    def test_reservoir_must_be_positive(self):
        from stmgcn_tpu.analysis import check_obs_overhead

        f = check_obs_overhead([("bad", self._cfg(reservoir=0))])
        assert any("positive sample bound" in x.message for x in f)
        assert check_obs_overhead([("ok", self._cfg(reservoir=1))]) == []

    def test_ring_bounds_only_checked_when_tracing(self):
        from stmgcn_tpu.analysis import check_obs_overhead
        from stmgcn_tpu.config import OBS_RING_BUDGET

        # tracing off: an absurd ring is dormant config, not a finding
        assert check_obs_overhead(
            [("off", self._cfg(trace=False, ring_capacity=0))]
        ) == []
        f = check_obs_overhead(
            [("on", self._cfg(trace=True, ring_capacity=0))]
        )
        assert any("unbounded span buffer" in x.message for x in f)
        f = check_obs_overhead(
            [("on", self._cfg(trace=True, ring_capacity=OBS_RING_BUDGET + 1))]
        )
        assert any("rotate" in x.message for x in f)
        assert check_obs_overhead(
            [("on", self._cfg(trace=True, ring_capacity=OBS_RING_BUDGET))]
        ) == []

    def test_configs_without_obs_section_skipped(self):
        from stmgcn_tpu.analysis import check_obs_overhead

        assert check_obs_overhead([("none", object())]) == []


class TestHealthOverheadRule:
    """Pass 2i: the health-overhead config contract — numeric-health
    knobs that make the telemetry layer a regression (or a no-op) of
    its own. Boundaries pinned exactly like obs-overhead: the budget
    itself is clean, one past it is flagged; cadence only gates once
    the training side is enabled."""

    @staticmethod
    def _cfg(**kw):
        from stmgcn_tpu.config import HealthConfig, preset

        cfg = preset("smoke")
        cfg.health = HealthConfig(**kw)
        return cfg

    def test_rule_registered_as_error(self):
        assert RULES["health-overhead"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_health_overhead

        assert check_health_overhead() == []

    def test_sketch_budget_boundary(self):
        from stmgcn_tpu.analysis import check_health_overhead
        from stmgcn_tpu.config import OBS_RESERVOIR_BUDGET

        # sketch bounds apply even with training health OFF — the
        # serving drift sketches exist in every serving process
        f = check_health_overhead(
            [("bad", self._cfg(sketch_size=OBS_RESERVOIR_BUDGET + 1))]
        )
        assert f and all(x.rule == "health-overhead" for x in f)
        assert all(x.severity == "error" for x in f)
        assert any("budget" in x.message for x in f)
        assert f[0].path == "<contract:health:bad>"
        assert check_health_overhead(
            [("ok", self._cfg(sketch_size=OBS_RESERVOIR_BUDGET))]
        ) == []
        f = check_health_overhead([("bad", self._cfg(sketch_size=0))])
        assert any("at least one bin" in x.message for x in f)

    def test_reservoir_budget_boundary(self):
        from stmgcn_tpu.analysis import check_health_overhead
        from stmgcn_tpu.config import OBS_RESERVOIR_BUDGET

        f = check_health_overhead(
            [("bad", self._cfg(reservoir=OBS_RESERVOIR_BUDGET + 1))]
        )
        assert any("budget" in x.message for x in f)
        assert check_health_overhead(
            [("ok", self._cfg(reservoir=OBS_RESERVOIR_BUDGET))]
        ) == []
        # 0 legitimately disables retention; negatives mean nothing
        assert check_health_overhead([("ok", self._cfg(reservoir=0))]) == []
        f = check_health_overhead([("bad", self._cfg(reservoir=-1))])
        assert any("reservoir" in x.message for x in f)

    def test_drift_without_baseline_flagged(self):
        from stmgcn_tpu.analysis import check_health_overhead

        f = check_health_overhead(
            [("bad", self._cfg(drift=True, baseline=False))]
        )
        assert any("never fire" in x.message for x in f)
        assert check_health_overhead(
            [("ok", self._cfg(drift=True, baseline=True))]
        ) == []

    def test_cadence_only_checked_when_enabled(self):
        from stmgcn_tpu.analysis import check_health_overhead

        # disabled: an absurd cadence is dormant config, not a finding
        assert check_health_overhead(
            [("off", self._cfg(enabled=False, every_k=0))]
        ) == []
        f = check_health_overhead(
            [("on", self._cfg(enabled=True, every_k=0))]
        )
        assert any("every_k" in x.message for x in f)
        assert check_health_overhead(
            [("on", self._cfg(enabled=True, every_k=1))]
        ) == []

    def test_configs_without_health_section_skipped(self):
        from stmgcn_tpu.analysis import check_health_overhead

        assert check_health_overhead([("none", object())]) == []


class TestContinualConfigRule:
    """Pass 2j: the continual-config contract — closed-loop knobs that
    turn an unattended learner into an outage. Boundaries pinned like
    the other contract rules: the budget/window/duty limits themselves
    are clean, one past them is flagged; trigger/retry/gate checks only
    gate once the loop is enabled."""

    @staticmethod
    def _cfg(drift_health=False, **kw):
        from stmgcn_tpu.config import ContinualConfig, preset

        cfg = preset("smoke")
        cfg.continual = ContinualConfig(**kw)
        if drift_health:
            cfg.health.drift = True
            cfg.health.baseline = True
        return cfg

    def test_rule_registered_as_error(self):
        assert RULES["continual-config"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_continual_config

        assert check_continual_config() == []

    def test_ring_window_boundary(self):
        from stmgcn_tpu.analysis import check_continual_config

        # smoke's window spec (3,1,1,24) needs burn_in+horizon = 169 rows
        f = check_continual_config([("bad", self._cfg(ring_capacity=168))])
        assert f and all(x.rule == "continual-config" for x in f)
        assert all(x.severity == "error" for x in f)
        assert f[0].path == "<contract:continual:bad>"
        assert any("training window" in x.message for x in f)
        assert check_continual_config(
            [("ok", self._cfg(ring_capacity=169))]
        ) == []

    def test_resident_budget_boundary(self):
        from stmgcn_tpu.analysis import check_continual_config

        # smoke: 10x10 grid, 1 channel, fp32 -> 400 B/row
        budget = 400 * 1000
        assert check_continual_config(
            [("ok", self._cfg(ring_capacity=1000))], budget_bytes=budget
        ) == []
        f = check_continual_config(
            [("bad", self._cfg(ring_capacity=1001))], budget_bytes=budget
        )
        assert any("resident budget" in x.message for x in f)

    def test_reorder_window_must_be_resident(self):
        from stmgcn_tpu.analysis import check_continual_config

        f = check_continual_config(
            [("bad", self._cfg(ring_capacity=200, reorder_window=200))]
        )
        assert any("reorder_window" in x.message for x in f)
        assert check_continual_config(
            [("ok", self._cfg(ring_capacity=200, reorder_window=199))]
        ) == []

    def test_duty_cycle_boundary(self):
        from stmgcn_tpu.analysis import check_continual_config

        # 8 supersteps x 625 ms every 10 s = duty 0.5 == max_duty: clean
        ok = self._cfg(enabled=True, cadence_s=10.0, superstep_ms=625.0)
        assert check_continual_config([("ok", ok)]) == []
        bad = self._cfg(enabled=True, cadence_s=10.0, superstep_ms=626.0)
        f = check_continual_config([("bad", bad)])
        assert any("starves serving" in x.message for x in f)
        # unmeasured superstep time: duty math is skipped, not guessed
        un = self._cfg(enabled=True, cadence_s=0.001, superstep_ms=0.0)
        assert check_continual_config([("ok", un)]) == []

    def test_drift_trigger_requires_baseline(self):
        from stmgcn_tpu.analysis import check_continual_config

        # cadence 0 = drift-only trigger; smoke's health has drift off
        f = check_continual_config(
            [("bad", self._cfg(enabled=True, cadence_s=0.0))]
        )
        assert any("never fire" in x.message for x in f)
        assert check_continual_config(
            [("ok", self._cfg(drift_health=True, enabled=True,
                              cadence_s=0.0))]
        ) == []

    def test_gate_thresholds_present_and_ordered(self):
        from stmgcn_tpu.analysis import check_continual_config

        f = check_continual_config(
            [("bad", self._cfg(enabled=True, cadence_s=60.0,
                               promote_update_ratio_max=0.0))]
        )
        assert any("rejects every candidate" in x.message for x in f)
        f = check_continual_config(
            [("bad", self._cfg(enabled=True, cadence_s=60.0,
                               promote_eval_margin=-0.1))]
        )
        assert any("promote_eval_margin" in x.message for x in f)
        f = check_continual_config(
            [("bad", self._cfg(enabled=True, cadence_s=60.0,
                               backoff_s=1.0, backoff_max_s=0.5))]
        )
        assert any("backoff" in x.message for x in f)

    def test_disabled_loop_is_dormant_config(self):
        from stmgcn_tpu.analysis import check_continual_config

        # loop off: absurd trigger/retry/gate knobs are dormant, but the
        # ring bounds still apply (a pre-filled ring exists without the
        # daemon)
        assert check_continual_config(
            [("off", self._cfg(enabled=False, backoff_s=-1.0,
                               promote_update_ratio_max=0.0))]
        ) == []
        f = check_continual_config(
            [("off", self._cfg(enabled=False, ring_capacity=0))]
        )
        assert any("ring_capacity" in x.message for x in f)

    def test_configs_without_continual_section_skipped(self):
        from stmgcn_tpu.analysis import check_continual_config

        assert check_continual_config([("none", object())]) == []


class TestFederationConfigRule:
    """Pass 2k: the federation-config contract — tier topology knobs
    that break deployment before any request is served. Boundaries
    pinned like the other contract rules: the limits themselves are
    clean, one past them is flagged; replica/budget/lifecycle checks
    only gate once the tier is enabled (ring-shape bounds always
    apply — the hash math exists with the router off)."""

    @staticmethod
    def _cfg(**kw):
        from stmgcn_tpu.config import FederationConfig, preset

        cfg = preset("smoke")
        cfg.federation = FederationConfig(**kw)
        return cfg

    def test_rule_registered_as_error(self):
        assert RULES["federation-config"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_federation_config

        assert check_federation_config() == []

    def test_replicas_vs_cities_boundary(self):
        from stmgcn_tpu.analysis import check_federation_config

        # smoke has 1 city: replicas == n_cities is the last clean point
        assert check_federation_config(
            [("ok", self._cfg(enabled=True, replicas=1))]
        ) == []
        f = check_federation_config(
            [("bad", self._cfg(enabled=True, replicas=2))]
        )
        assert f and all(x.rule == "federation-config" for x in f)
        assert all(x.severity == "error" for x in f)
        assert f[0].path == "<contract:federation:bad>"
        assert any("permanently idle" in x.message for x in f)

    def test_ring_points_vs_imbalance_boundary(self):
        from stmgcn_tpu.analysis import check_federation_config

        # imbalance 0.5 needs 4/0.25 = 16 points: 1x16 clean, 1x15 not
        assert check_federation_config(
            [("ok", self._cfg(enabled=True, replicas=1, vnodes=16))]
        ) == []
        f = check_federation_config(
            [("bad", self._cfg(enabled=True, replicas=1, vnodes=15))]
        )
        assert any("bound imbalance" in x.message for x in f)
        with_bound = self._cfg(enabled=True, replicas=1, vnodes=15,
                               imbalance_max=1.0)
        assert check_federation_config([("ok", with_bound)]) == []

    def test_global_budget_vs_local_bound_boundary(self):
        from stmgcn_tpu.config import ServingConfig
        from stmgcn_tpu.analysis import check_federation_config

        ok = self._cfg(enabled=True, replicas=1,
                       global_queue_bound_rows=64)
        ok.serving = ServingConfig(buckets=(1, 16), max_batch=16,
                                   queue_bound_rows=64)
        assert check_federation_config([("ok", ok)]) == []
        bad = self._cfg(enabled=True, replicas=1,
                        global_queue_bound_rows=63)
        bad.serving = ok.serving
        f = check_federation_config([("bad", bad)])
        assert any("per-replica bound" in x.message for x in f)

    def test_global_budget_vs_top_rung_boundary(self):
        from stmgcn_tpu.config import ServingConfig
        from stmgcn_tpu.analysis import check_federation_config

        # no local queue bound, so only the top-rung floor applies
        srv = ServingConfig(buckets=(1, 16), max_batch=16)
        ok = self._cfg(enabled=True, replicas=1, global_queue_bound_rows=16)
        ok.serving = srv
        assert check_federation_config([("ok", ok)]) == []
        bad = self._cfg(enabled=True, replicas=1, global_queue_bound_rows=15)
        bad.serving = srv
        f = check_federation_config([("bad", bad)])
        assert any("top ladder rung" in x.message for x in f)

    def test_handover_must_not_exceed_drain(self):
        from stmgcn_tpu.analysis import check_federation_config

        assert check_federation_config(
            [("ok", self._cfg(enabled=True, replicas=1,
                              drain_timeout_s=2.0, handover_timeout_s=2.0))]
        ) == []
        f = check_federation_config(
            [("bad", self._cfg(enabled=True, replicas=1,
                               drain_timeout_s=2.0,
                               handover_timeout_s=2.001))]
        )
        assert any("never be allowed longer than a full drain" in x.message
                   for x in f)
        f = check_federation_config(
            [("bad", self._cfg(enabled=True, replicas=1,
                               drain_timeout_s=0.0))]
        )
        assert any("timeouts must be positive" in x.message for x in f)

    def test_disabled_tier_is_dormant_config(self):
        from stmgcn_tpu.analysis import check_federation_config

        # tier off: absurd replica/budget/lifecycle knobs are dormant,
        # but the ring-shape bounds still apply (the hash math is global)
        assert check_federation_config(
            [("off", self._cfg(enabled=False, replicas=99,
                               handover_timeout_s=99.0))]
        ) == []
        f = check_federation_config(
            [("off", self._cfg(enabled=False, vnodes=0))]
        )
        assert any("vnodes" in x.message for x in f)

    def test_configs_without_federation_section_skipped(self):
        from stmgcn_tpu.analysis import check_federation_config

        assert check_federation_config([("none", object())]) == []


class TestResidentMemoryRule:
    """Pass 2f: the resident-memory footprint contract (pure config math
    — the same arithmetic as DemandDataset.resident_nbytes/nbytes,
    checked against Trainer.RESIDENT_CAP_BYTES at lint time)."""

    def test_rule_registered_as_error(self):
        assert RULES["resident-memory"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_resident_memory

        assert check_resident_memory() == []

    def test_estimate_matches_dataset_math(self):
        """The config-only estimate equals the smoke preset's real
        dataset footprints, byte for byte (window-free 4.5x smaller)."""
        from stmgcn_tpu.analysis.resident_check import estimate_resident_bytes
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset

        cfg = preset("smoke")
        est = estimate_resident_bytes(cfg)
        assert (est["series_bytes"], est["materialized_bytes"]) == (270836, 1209600)
        data = synthetic_dataset(rows=cfg.data.rows,
                                 n_timesteps=cfg.data.n_timesteps)
        ds = DemandDataset(
            data, WindowSpec(cfg.data.serial_len, cfg.data.daily_len,
                             cfg.data.weekly_len, cfg.data.day_timesteps)
        )
        assert est["series_bytes"] == ds.resident_nbytes
        assert est["materialized_bytes"] == ds.nbytes

    def test_budget_margin_is_the_documented_boundary(self):
        """At N=2500 the window-free footprint crosses the 1 GiB budget
        between T=107331 (3,152 bytes inside) and T=107332 — the check
        must know the boundary exactly, like collective-shape's 150-vs-156
        halo margin."""
        from stmgcn_tpu.analysis.resident_check import (
            check_resident_memory, estimate_resident_bytes,
        )
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.train.trainer import Trainer

        assert Trainer.RESIDENT_CAP_BYTES == 1 << 30
        cfg = preset("smoke")
        cfg.train.data_placement = "resident"
        cfg.data.rows = 50

        cfg.data.n_timesteps = 107331
        assert estimate_resident_bytes(cfg)["series_bytes"] == 1073738672
        assert check_resident_memory([("edge", cfg)]) == []

        cfg.data.n_timesteps = 107332
        f = check_resident_memory([("over", cfg)])
        assert [x.rule for x in f] == ["resident-memory"]
        assert f[0].severity == "error"
        assert f[0].path == "<contract:resident:over>"
        assert "window-free series" in f[0].message

    def test_materialized_fallback_fires_with_hint(self):
        """window_free=False forces the ~seq_len-x materialized windows:
        a config whose series fits but whose windows do not must fire and
        say the window-free path would have fit."""
        from stmgcn_tpu.analysis import check_resident_memory
        from stmgcn_tpu.config import preset

        cfg = preset("smoke")
        cfg.train.data_placement = "resident"
        cfg.train.window_free = False
        cfg.data.rows = 50
        cfg.data.n_timesteps = 30000
        f = check_resident_memory([("mat", cfg)])
        assert any("materialized windows" in x.message for x in f)
        assert any("window-free series would be" in x.message for x in f)
        cfg.train.window_free = None  # the default path fits fine
        assert check_resident_memory([("wf", cfg)]) == []

    def test_resident_on_mesh_fires(self):
        from stmgcn_tpu.analysis import check_resident_memory
        from stmgcn_tpu.config import preset

        bad = preset("multicity")  # dp=8 mesh
        bad.train.data_placement = "resident"
        bad.train.window_free = False  # materialized windows on a mesh
        f = check_resident_memory([("bad", bad)])
        assert [x.rule for x in f] == ["resident-memory"]
        assert any("mesh" in x.message for x in f)
        # the window-free composition (the composed multi-chip path) is
        # legal now — no finding without the materialized forcing
        ok = preset("multicity")
        ok.train.data_placement = "resident"
        assert check_resident_memory([("ok", ok)]) == []

    def test_auto_placement_skipped(self):
        """"auto" degrades to streaming by design — an oversized auto
        config must not fire."""
        from stmgcn_tpu.analysis import check_resident_memory
        from stmgcn_tpu.config import preset

        cfg = preset("smoke")
        cfg.data.rows = 50
        cfg.data.n_timesteps = 500000  # far past the budget
        assert cfg.train.data_placement == "auto"
        assert check_resident_memory([("big", cfg)]) == []


class TestFleetShapeClassRule:
    """Pass 2g: the fleet-shape-class planner contract (pure config math
    — the same plan_shape_classes the trainer runs at construction,
    checked against knob validity, city coverage, and the per-core
    resident budget at lint time)."""

    @staticmethod
    def _engaged_multicity():
        from stmgcn_tpu.config import preset

        cfg = preset("multicity")  # cities N=144 and N=100
        cfg.train.steps_per_superstep = 4
        return cfg

    def test_rule_registered_as_error(self):
        assert RULES["fleet-shape-class"].severity == "error"

    def test_all_presets_clean(self):
        from stmgcn_tpu.analysis import check_fleet_shape_classes

        assert check_fleet_shape_classes() == []

    def test_disengaged_config_skipped(self):
        """fleet=None with S=1 never takes the fleet path — even absurd
        knobs must not fire."""
        from stmgcn_tpu.analysis import check_fleet_shape_classes
        from stmgcn_tpu.config import preset

        cfg = preset("multicity")
        assert cfg.train.steps_per_superstep == 1
        cfg.train.fleet_max_classes = 0
        assert check_fleet_shape_classes([("off", cfg)]) == []

    def test_invalid_knobs_fire(self):
        from stmgcn_tpu.analysis import check_fleet_shape_classes

        cfg = self._engaged_multicity()
        cfg.train.fleet_max_classes = 0
        f = check_fleet_shape_classes([("bad", cfg)])
        assert [x.rule for x in f] == ["fleet-shape-class"]
        assert "fleet_max_classes" in f[0].message
        assert f[0].path == "<contract:fleet:bad>"

        cfg = self._engaged_multicity()
        cfg.train.fleet_max_pad_waste = 1.0
        f = check_fleet_shape_classes([("bad", cfg)])
        assert any("fleet_max_pad_waste" in x.message for x in f)

    def test_fleet_on_homogeneous_fires(self):
        from stmgcn_tpu.analysis import check_fleet_shape_classes
        from stmgcn_tpu.config import preset

        cfg = preset("smoke")
        cfg.train.fleet = True
        f = check_fleet_shape_classes([("homog", cfg)])
        assert any("homogeneous" in x.message for x in f)

    def test_fleet_on_streamed_data_fires(self):
        from stmgcn_tpu.analysis import check_fleet_shape_classes

        cfg = self._engaged_multicity()
        cfg.train.fleet = True
        cfg.train.data_placement = "stream"
        f = check_fleet_shape_classes([("stream", cfg)])
        assert any("stream" in x.message for x in f)

    def test_uncovered_city_boundary(self):
        """N=100 in the N=144 rung pads 44/144 of its nodes. The planner
        assigns at waste == threshold exactly and drops the city one
        epsilon below — the check must know that boundary."""
        from stmgcn_tpu.analysis import check_fleet_shape_classes

        cfg = self._engaged_multicity()
        cfg.train.fleet_max_classes = 1
        cfg.train.fleet_max_pad_waste = 44 / 144
        assert check_fleet_shape_classes([("fit", cfg)]) == []

        cfg.train.fleet_max_pad_waste = 44 / 144 - 1e-9
        f = check_fleet_shape_classes([("tight", cfg)])
        assert len(f) == 1 and "fit no shape class" in f[0].message
        assert "[1]" in f[0].message  # the dropped city is named

        # a second class rescues the small city
        cfg.train.fleet_max_classes = 2
        assert check_fleet_shape_classes([("two", cfg)]) == []

    def test_class_footprint_budget_boundary(self):
        """The per-class resident estimate vs the budget, exactly at the
        byte boundary (strictly-greater fires, equal fits)."""
        from stmgcn_tpu.analysis import check_fleet_shape_classes
        from stmgcn_tpu.analysis.fleet_check import estimate_fleet_plan

        cfg = self._engaged_multicity()
        plan, class_bytes = estimate_fleet_plan(cfg)
        assert [c.n_nodes for c in plan.classes] == [144]
        assert plan.unassigned == ()
        (nbytes,) = class_bytes

        assert check_fleet_shape_classes(
            [("fit", cfg)], budget_bytes=nbytes) == []
        f = check_fleet_shape_classes([("oom", cfg)], budget_bytes=nbytes - 1)
        assert len(f) == 1 and "resident bytes" in f[0].message
        assert "N=144" in f[0].message

    def test_estimate_matches_trainer_stack_math(self):
        """The support-stack term is members x M x K x rung^2 x 4 — pin
        the multicity estimate so the arithmetic cannot drift silently."""
        from stmgcn_tpu.analysis.fleet_check import estimate_fleet_plan
        from stmgcn_tpu.data.windowing import WindowSpec

        cfg = self._engaged_multicity()
        plan, (nbytes,) = estimate_fleet_plan(cfg)
        d, m = cfg.data, cfg.model
        spec = WindowSpec(d.serial_len, d.daily_len, d.weekly_len,
                          d.day_timesteps, horizon=d.horizon)
        series = sum(t * 144 * 4 for t in d.city_timesteps)
        targets = sum(4 * spec.n_samples(t) for t in d.city_timesteps)
        stack = 2 * m.m_graphs * m.n_supports * 144 * 144 * 4
        assert nbytes == series + targets + stack


# -- PR 7: whole-program lint, Pallas static checks, closure identity ----

_XMOD_FIXTURE = {
    "pkg.model": textwrap.dedent(
        """
        import jax
        from pkg.helpers import readback

        @jax.jit
        def step(x):
            return readback(x)
        """
    ),
    "pkg.helpers": textwrap.dedent(
        """
        def readback(x):
            return float(x)
        """
    ),
}


class TestProgramDB:
    """program_db: the repo-wide database behind whole-program mode."""

    def test_cross_module_promotion_with_chain(self):
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources(_XMOD_FIXTURE)
        extras = db.module_extras("pkg.helpers")
        assert extras == {
            "readback": ("pkg.model:step", "pkg.helpers:readback"),
        }
        f = lint_source(
            _XMOD_FIXTURE["pkg.helpers"], "pkg/helpers.py",
            extra_reachable=extras,
        )
        assert [x.rule for x in f] == ["host-sync-in-jit"]
        assert f[0].chain == ("pkg.model:step", "pkg.helpers:readback")
        assert "(cross-module)" in f[0].message

    def test_reexport_chain_through_init(self):
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({
            "pkg.ops": "from pkg.ops_impl import make\n",
            "pkg.ops_impl": "def make():\n    return 1\n",
            "pkg.user": textwrap.dedent(
                """
                import jax
                from pkg.ops import make

                @jax.jit
                def step(x):
                    return make()
                """
            ),
        })
        assert db.resolve_symbol("pkg.ops.make") == "pkg.ops_impl:make"
        assert "pkg.ops_impl:make" in db.global_reachability()

    def test_imported_function_handed_to_tracer_seeds_root(self):
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({
            "pkg.body": "def body(c, x):\n    return c, float(x)\n",
            "pkg.driver": textwrap.dedent(
                """
                import jax
                from pkg.body import body

                def run(xs):
                    return jax.lax.scan(body, 0, xs)
                """
            ),
        })
        assert "pkg.body:body" in db.roots
        assert "pkg.body:body" in db.global_reachability()

    def test_dynamic_dispatch_never_crosses_modules(self):
        """self.foo()/unknown-attr calls stay per-module — the
        zero-new-false-positives precision contract."""
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({
            "pkg.a": textwrap.dedent(
                """
                import jax

                @jax.jit
                def step(obj):
                    return obj.readback(1)
                """
            ),
            "pkg.b": "def readback(x):\n    return float(x)\n",
        })
        assert db.module_extras("pkg.b") == {}


class TestWholeProgramOnTree:
    """The acceptance pins: real cross-module gain, zero new findings."""

    def test_cross_module_gain_nonempty_and_named(self):
        import os

        from stmgcn_tpu import analysis
        from stmgcn_tpu.analysis.program_db import ProgramDB

        root = os.path.dirname(analysis.__file__)
        pkg_root = os.path.dirname(root)
        db = ProgramDB.from_root(pkg_root, package="stmgcn_tpu")
        gain = db.cross_module_gain()
        assert len(gain) >= 1
        # make_conv is reachable only via models/st_mgcn's jitted path —
        # the canonical function no per-module index can see
        assert any(q.endswith("chebconv:make_conv") for q in gain)
        for q, chain in gain.items():
            assert chain[-1] == q and len(chain) >= 2

    def test_whole_program_adds_zero_findings_on_clean_tree(self):
        assert lint_package(whole_program=True) == []
        assert lint_package(whole_program=False) == []


class TestClosureIdentityRule:
    def test_partial_at_static_position(self):
        f = lint(
            """
            import functools
            import jax

            def apply(fn, x):
                return fn(x)

            def scale(x, k):
                return x * k

            g = jax.jit(apply, static_argnums=(0,))

            def run(x):
                return g(functools.partial(scale, k=2.0), x)
            """
        )
        assert _rules(f) == {"closure-identity"}

    def test_bound_method_at_static_position(self):
        f = lint(
            """
            import jax

            def apply(fn, x):
                return fn(x)

            class Model:
                def forward(self, x):
                    return x

            g = jax.jit(apply, static_argnames=("fn",))

            def run(m, x):
                return g(fn=m.forward, x=x)
            """
        )
        assert _rules(f) == {"closure-identity"}

    def test_nested_def_at_static_position(self):
        f = lint(
            """
            import jax

            def apply(fn, x):
                return fn(x)

            g = jax.jit(apply, static_argnums=(0,))

            def run(x, k):
                def scaled(v):
                    return v * k
                return g(scaled, x)
            """
        )
        assert _rules(f) == {"closure-identity"}

    def test_jit_bound_in_loop(self):
        f = lint(
            """
            import jax

            def step(x):
                return x + 1

            def train(xs):
                out = []
                for x in xs:
                    f2 = jax.jit(step)
                    out.append(f2(x))
                return out
            """
        )
        assert _rules(f) == {"closure-identity"}

    def test_aot_compile_in_loop_ok(self):
        """jax.jit(fn).lower(...).compile() per bucket is the loop-safe
        AOT idiom (serving/engine.py) — must not flag."""
        f = lint(
            """
            import jax

            def step(x):
                return x + 1

            def build(buckets):
                progs = {}
                for b in buckets:
                    progs[b] = jax.jit(step).lower(b).compile()
                return progs
            """
        )
        assert f == []

    def test_module_level_def_at_static_position_ok(self):
        f = lint(
            """
            import jax

            def apply(fn, x):
                return fn(x)

            def scale(x):
                return x * 2.0

            g = jax.jit(apply, static_argnums=(0,))

            def run(x):
                return g(scale, x)
            """
        )
        assert f == []


class TestPallasStaticCheck:
    def test_extracts_both_kernel_sites(self):
        from stmgcn_tpu.analysis.pallas_check import extract_pallas_sites

        sites = {s.fn for s in extract_pallas_sites()}
        assert sites == {"_run_fwd", "_fused_bwd"}

    def test_shipped_kernels_pass(self):
        from stmgcn_tpu.analysis.pallas_check import check_pallas_kernels

        findings = check_pallas_kernels()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_flags_the_known_fp32_forward_oom(self):
        """The calibration pin: at the pre-halving fp32 128-row block the
        estimator must reproduce the real Mosaic AOT verdict — an 18.04 MB
        scoped-VMEM allocation vs the 16 MB budget (bench_stderr.log,
        2026-07-29; benchmarks/mosaic_compile_check.py)."""
        from stmgcn_tpu.analysis.pallas_check import (
            VMEM_BUDGET_BYTES,
            KernelPoint,
            check_pallas_kernels,
            extract_pallas_sites,
            vmem_estimate,
        )

        oom = KernelPoint(dtype="float32", fwd_rows=128, bwd_rows=64)
        fwd = [s for s in extract_pallas_sites() if s.fn == "_run_fwd"][0]
        est = vmem_estimate(fwd, oom)
        assert abs(est["estimate_mib"] - 18.04) < 0.01
        assert est["estimate_bytes"] > VMEM_BUDGET_BYTES

        findings = check_pallas_kernels(points=[oom])
        assert [f.rule for f in findings] == ["pallas-vmem"]
        assert "18.04 MiB" in findings[0].message
        assert findings[0].severity == "error"

    def test_shipped_estimates_have_headroom(self):
        """Every shipped (dtype, block) point sits under ~10 MiB — the
        halved blocks bought real margin, not a squeak-by."""
        from stmgcn_tpu.analysis.pallas_check import (
            KernelPoint,
            extract_pallas_sites,
            vmem_estimate,
        )

        for dtype in ("float32", "bfloat16"):
            for site in extract_pallas_sites():
                est = vmem_estimate(site, KernelPoint(dtype=dtype))
                assert est["estimate_mib"] < 10.0, (site.fn, dtype, est)


_SPMM_BAD_COVERAGE_FIXTURE = '''
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _spmm_call(data, idx, x, n, tile, interpret):
    r, c_max = idx.shape
    n_pad = r * tile
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r, mb, c_max),
        in_specs=[
            pl.BlockSpec((1, 1, tile, tile), lambda i, j, c, idx_ref: (i, c, 0, 0)),
            pl.BlockSpec((tile, tm), lambda i, j, c, idx_ref: (idx_ref[i, c], j)),
        ],
        out_specs=pl.BlockSpec((tile // 2, tm), lambda i, j, c, idx_ref: (i, j)),
    )
    out = pl.pallas_call(
        _spmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_pad, m_pad), jnp.float32),
        interpret=interpret,
    )(idx, data, x_pad)
    return out
'''


class TestSpmmPallasStaticCheck:
    """PR 13 satellite: the site model extends to ops/spmm.py's three
    PrefetchScalarGridSpec launches — keyword grid_spec unwrapping,
    post-prefetch operand alignment, dynamic (idx_ref-gathered) axes,
    and the VMEM boundary at the configured tile size."""

    def _spmm_sites(self):
        from stmgcn_tpu.analysis.pallas_check import (
            _default_kernel_path,
            extract_pallas_sites,
        )

        return extract_pallas_sites(_default_kernel_path("ops/spmm.py"))

    def test_extracts_all_three_spmm_sites(self):
        sites = self._spmm_sites()
        assert {s.fn for s in sites} == {
            "_spmm_call", "_stack_fwd_call", "_stack_bwd_call"
        }
        for s in sites:
            # PrefetchScalarGridSpec: the index list is operand 0 with
            # no BlockSpec of its own
            assert s.num_scalar_prefetch == 1
            assert len(s.in_specs) == len(s.operands) - 1
            assert s.grid is not None and s.out_specs and s.out_shape

    def test_repo_has_no_uncovered_pallas_call_site(self):
        """Every pl.pallas_call in the package is in a module the
        checker models — a new kernel file must extend KERNEL_MODULES
        (and _site_env) or this trips."""
        import os

        import stmgcn_tpu
        from stmgcn_tpu.analysis.pallas_check import (
            KERNEL_MODULES,
            _default_kernel_path,
            extract_pallas_sites,
        )

        pkg = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
        covered = {
            os.path.normpath(_default_kernel_path(m)) for m in KERNEL_MODULES
        }
        offenders = []
        for root, _, files in os.walk(pkg):
            for name in files:
                if not name.endswith(".py"):
                    continue
                path = os.path.normpath(os.path.join(root, name))
                if extract_pallas_sites(path) and path not in covered:
                    offenders.append(path)
        assert offenders == []

    def test_dynamic_gather_axis_streams_without_coverage_claim(self):
        from stmgcn_tpu.analysis.pallas_check import (
            SpmmKernelPoint,
            _site_blocks,
        )

        fwd = [s for s in self._spmm_sites() if s.fn == "_stack_fwd_call"][0]
        _, uses = _site_blocks(fwd, SpmmKernelPoint())
        x = [u for u in uses if u.operand == "<arg2>"][0]
        assert x.streamed
        # axis 1 is idx_ref[ki, i, c]-gathered: dynamic, never a bare
        # grid param — the static coverage check must skip it
        assert x.roles[1] == ("dynamic", None)
        data = [u for u in uses if u.operand == "data"][0]
        assert data.streamed and ("param", 0) in data.roles

    def test_vmem_boundary_at_configured_tile(self):
        """tile=512 clears the 16 MiB budget with headroom; tile=1024
        blows it at every site — the pallas-vmem boundary the tile-plan
        config rule mirrors."""
        from stmgcn_tpu.analysis.pallas_check import (
            SpmmKernelPoint,
            check_pallas_kernels,
            vmem_estimate,
        )

        ok = check_pallas_kernels(spmm_points=[SpmmKernelPoint(tile=512)])
        assert [f for f in ok if "spmm" in f.path or "_call" in f.message] == []
        big = SpmmKernelPoint(tile=1024)
        findings = check_pallas_kernels(spmm_points=[big])
        fired = {f.message.split("`")[1] for f in findings
                 if f.rule == "pallas-vmem"}
        assert fired == {"_spmm_call", "_stack_fwd_call", "_stack_bwd_call"}
        est = vmem_estimate(
            [s for s in self._spmm_sites() if s.fn == "_spmm_call"][0], big
        )
        assert est["estimate_mib"] > 16.0
        small = vmem_estimate(
            [s for s in self._spmm_sites() if s.fn == "_spmm_call"][0],
            SpmmKernelPoint(tile=512),
        )
        assert small["estimate_mib"] < 10.0

    def test_shipped_default_point_passes(self):
        from stmgcn_tpu.analysis.pallas_check import check_pallas_kernels

        findings = check_pallas_kernels()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_bad_grid_coverage_fires_blockspec(self, tmp_path):
        """A half-height out block under the same grid leaves half the
        out rows unwritten — the static coverage check must fire."""
        from stmgcn_tpu.analysis.pallas_check import (
            SpmmKernelPoint,
            check_pallas_kernels,
        )

        p = tmp_path / "bad_spmm.py"
        p.write_text(_SPMM_BAD_COVERAGE_FIXTURE)
        findings = check_pallas_kernels(
            path=str(p), spmm_points=[SpmmKernelPoint()]
        )
        assert [f.rule for f in findings] == ["pallas-blockspec"]
        assert "covers" in findings[0].message

    def test_lstm_point_against_spmm_site_is_out_of_sync(self):
        from stmgcn_tpu.analysis.pallas_check import (
            KernelPoint,
            _check_site,
        )

        site = [s for s in self._spmm_sites() if s.fn == "_spmm_call"][0]
        findings = _check_site(site, KernelPoint())
        assert [f.rule for f in findings] == ["pallas-blockspec"]
        assert "out of sync" in findings[0].message


class TestTilePlanRule:
    """PR 13 satellite: the tile-plan config rule — pure config math
    over the tiled-support knobs, with the VMEM boundary mirroring the
    pallas-vmem fixtures (tile=512 pass, tile=1024 fire)."""

    def _tiled(self, **kw):
        from stmgcn_tpu.config import preset

        cfg = preset("smoke")
        cfg.model.tiled = True
        for k, v in kw.items():
            setattr(cfg.model, k, v)
        return cfg

    def test_rule_registered(self):
        from stmgcn_tpu.analysis.rules import RULES

        assert RULES["tile-plan"].severity == "error"

    def test_shipped_presets_clean(self):
        from stmgcn_tpu.analysis.tiling_check import check_tile_plan

        assert check_tile_plan() == []

    def test_untiled_config_is_a_no_op(self):
        from stmgcn_tpu.config import preset
        from stmgcn_tpu.analysis.tiling_check import tile_plan_violations

        assert tile_plan_violations(preset("smoke").model, 8192) == []

    def test_vmem_boundary_512_pass_1024_fire(self):
        from stmgcn_tpu.analysis.pallas_check import VMEM_BUDGET_BYTES
        from stmgcn_tpu.analysis.tiling_check import (
            tile_plan_violations,
            tiled_spmm_vmem_estimate,
        )

        ok = self._tiled(tile_size=512)
        assert tile_plan_violations(ok.model, 8192) == []
        assert tiled_spmm_vmem_estimate(512) < VMEM_BUDGET_BYTES
        bad = self._tiled(tile_size=1024)
        msgs = tile_plan_violations(bad.model, 8192)
        assert len(msgs) == 1 and "VMEM" in msgs[0] and "25.28" in msgs[0]
        assert tiled_spmm_vmem_estimate(1024) > VMEM_BUDGET_BYTES

    def test_node_padding_waste_boundary(self):
        """waste = 1 - N/padded against the budget: one node above the
        boundary joins, at/below fires — pinned at tile=128, budget
        0.75 (default), where the boundary N is exactly 32."""
        from stmgcn_tpu.analysis.tiling_check import tile_plan_violations

        cfg = self._tiled(tile_size=128)
        assert cfg.model.tile_waste_budget == 0.75
        assert tile_plan_violations(cfg.model, 32) == []  # waste == budget
        msgs = tile_plan_violations(cfg.model, 31)  # one past it
        assert len(msgs) == 1 and "tile_waste_budget" in msgs[0]

    def test_knob_ranges(self):
        from stmgcn_tpu.analysis.tiling_check import tile_plan_violations

        assert "tile_size" in tile_plan_violations(
            self._tiled(tile_size=0).model, 100
        )[0]
        assert "tile_waste_budget" in tile_plan_violations(
            self._tiled(tile_waste_budget=0.0).model, 100
        )[0]
        assert "mutually exclusive" in tile_plan_violations(
            self._tiled(sparse=True).model, 100
        )[0]

    def test_mesh_conflict_and_hetero_cities_via_check(self):
        from stmgcn_tpu.config import MeshConfig, preset
        from stmgcn_tpu.analysis.tiling_check import check_tile_plan

        cfg = preset("multicity")
        cfg.model.tiled = True
        findings = check_tile_plan([("multicity-tiled", cfg)])
        assert [f.rule for f in findings] == ["tile-plan"]
        assert "mesh" in findings[0].message
        assert findings[0].path == "<contract:tile-plan:multicity-tiled>"
        cfg.mesh = MeshConfig()
        assert check_tile_plan([("multicity-tiled", cfg)]) == []
        # per-city sizes: a tile too large for the smallest city fires
        # for that city only
        cfg.model.tile_size = 512
        cfg.model.tile_waste_budget = 0.5
        findings = check_tile_plan([("multicity-tiled", cfg)])
        assert all("city" in f.message for f in findings)
        assert len(findings) == 2  # both 144- and 100-node cities


class TestWholeProgramSuppression:
    """Suppression semantics under whole-program mode (satellite c)."""

    def _fixture(self, suppress):
        helpers = _XMOD_FIXTURE["pkg.helpers"]
        if suppress:
            helpers = helpers.replace(
                "return float(x)",
                "return float(x)  # stmgcn: ignore[host-sync-in-jit]",
            )
        return {"pkg.model": _XMOD_FIXTURE["pkg.model"], "pkg.helpers": helpers}

    def test_cross_module_finding_suppressible_at_reported_line(self):
        from stmgcn_tpu.analysis.program_db import ProgramDB

        srcs = self._fixture(suppress=True)
        db = ProgramDB.from_sources(srcs)
        f = lint_source(
            srcs["pkg.helpers"], "pkg/helpers.py",
            extra_reachable=db.module_extras("pkg.helpers"),
        )
        assert f == []

    def test_suppressed_surfaces_under_include_suppressed(self):
        from stmgcn_tpu.analysis.program_db import ProgramDB
        from stmgcn_tpu.analysis.report import render_json

        srcs = self._fixture(suppress=True)
        db = ProgramDB.from_sources(srcs)
        f = lint_source(
            srcs["pkg.helpers"], "pkg/helpers.py",
            extra_reachable=db.module_extras("pkg.helpers"),
            include_suppressed=True,
        )
        assert [x.rule for x in f] == ["host-sync-in-jit"]
        assert f[0].suppressed is True
        assert f[0].chain == ("pkg.model:step", "pkg.helpers:readback")
        payload = json.loads(render_json(f))
        # listed but never counted: suppressed findings cannot gate
        assert payload["errors"] == 0 and payload["warnings"] == 0
        assert payload["findings"][0]["suppressed"] is True


def _line_of(src, snippet):
    """1-based line of the unique source line containing ``snippet`` —
    pins a finding to its exact boundary without hand-counted numbers."""
    hits = [i for i, ln in enumerate(src.splitlines(), 1) if snippet in ln]
    assert len(hits) == 1, (snippet, hits)
    return hits[0]


_UNGUARDED_SRC = textwrap.dedent(
    """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def read(self):
            return self._n
    """
)

_CONDVAR_SRC = textwrap.dedent(
    """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self._items = []

        def put(self, x):
            with self._cond:
                self._items.append(x)
                self._cond.notify()

        def get_good(self):
            with self._cond:
                while not self._items:
                    self._cond.wait()
                return self._items.pop()
    """
)

_THREAD_SRC = textwrap.dedent(
    """
    import threading
    import time

    class Worker:
        def __init__(self):
            self._t = threading.Thread(target=self._run)

        def start(self):
            self._t.start()

        def _run(self):
            pass
    """
)

# the two-module deadlock shape: each class calls into the *other*
# module's singleton while holding its own lock — only resolvable
# through the class model (module-global instance types)
_CYCLE_A = textwrap.dedent(
    """
    import threading

    from pkg.b import OTHER

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def poke(self):
            with self._lock:
                pass

        def cross(self):
            with self._lock:
                OTHER.poke()

    ROOT = A()
    """
)

_CYCLE_B = textwrap.dedent(
    """
    import threading

    from pkg.a import ROOT

    class B:
        def __init__(self):
            self._lock = threading.Lock()

        def poke(self):
            with self._lock:
                pass

        def cross(self):
            with self._lock:
                ROOT.poke()

    OTHER = B()
    """
)


class TestClassModel:
    """program_db's class awareness: the facts the concurrency rules
    consume (sync fields, condvar owners, type evidence)."""

    def test_sync_fields_and_attr_types(self):
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({
            "pkg.m": textwrap.dedent(
                """
                import threading
                import queue

                class Stats:
                    def __init__(self):
                        self.n = 0

                class Engine:
                    def __init__(self, poll):
                        self._lock = threading.Lock()
                        self._cond = threading.Condition(self._lock)
                        self._q = queue.Queue()
                        self._t = threading.Thread(target=self._run,
                                                   daemon=True)
                        self._stats = Stats()

                    def _run(self):
                        pass
                """
            ),
        })
        ci = db.classes["pkg.m:Engine"]
        assert ci.locks == {"_lock"}
        assert ci.condvars == {"_cond": "_lock"}
        assert ci.queues == {"_q"}
        assert ci.threads == {"_t": True}  # daemon kwarg captured
        assert ci.attr_types == {"_stats": "pkg.m:Stats"}
        assert set(ci.methods) == {"__init__", "_run"}

    def test_conflicting_assignment_poisons_type(self):
        """zero-false-positive contract: an attr assigned two different
        ways is *untyped*, not guessed."""
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({
            "pkg.m": textwrap.dedent(
                """
                class Stats:
                    pass

                class Engine:
                    def __init__(self, stats):
                        self._stats = Stats()

                    def attach(self, other):
                        self._stats = other
                """
            ),
        })
        assert db.classes["pkg.m:Engine"].attr_types == {}

    def test_optional_none_assignment_keeps_type(self):
        """the ``self._t = None`` / later ``self._t = Thread(...)``
        idiom stays a thread field — None never poisons; no daemon
        kwarg pins the ``threading.Thread`` default (non-daemon)."""
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({
            "pkg.m": textwrap.dedent(
                """
                import threading

                class W:
                    def __init__(self):
                        self._t = None

                    def go(self):
                        self._t = threading.Thread(target=self.go)
                """
            ),
        })
        assert db.classes["pkg.m:W"].threads == {"_t": False}


class TestConcurrencyRules:
    """Each rule's fire/pass boundary on a seeded fixture (satellite c)."""

    def _run(self, sources, **kw):
        from stmgcn_tpu.analysis.concurrency_check import check_concurrency
        from stmgcn_tpu.analysis.program_db import ProgramDB

        return check_concurrency(
            ProgramDB.from_sources(sources, type_informed=True), **kw)

    def test_all_four_rules_registered_as_errors(self):
        for rule in ("unguarded-attr", "lock-order-cycle",
                     "condvar-discipline", "thread-lifecycle"):
            assert RULES[rule].severity == "error"

    def test_unguarded_read_fires_with_cross_method_chain(self):
        f = self._run({"pkg.box": _UNGUARDED_SRC})
        assert [(x.rule, x.line) for x in f] == [
            ("unguarded-attr", _line_of(_UNGUARDED_SRC, "return self._n")),
        ]
        assert f[0].chain == ("pkg.box:Box.bump", "pkg.box:Box.read")
        assert "`self._n`" in f[0].message and "`self._lock`" in f[0].message

    def test_guarded_twin_is_clean(self):
        guarded = _UNGUARDED_SRC.replace(
            "def read(self):\n        return self._n",
            "def read(self):\n        with self._lock:\n"
            "            return self._n",
        )
        assert self._run({"pkg.box": guarded}) == []

    def test_wait_outside_while_fires(self):
        bad = _CONDVAR_SRC.replace(
            "            while not self._items:\n"
            "                self._cond.wait()",
            "            self._cond.wait()  # BAD",
        )
        f = self._run({"pkg.q": bad})
        assert [(x.rule, x.line) for x in f] == [
            ("condvar-discipline", _line_of(bad, "# BAD")),
        ]
        assert "while" in f[0].message

    def test_notify_outside_owning_lock_fires(self):
        bad = _CONDVAR_SRC.replace(
            "    def put(self, x):",
            "    def kick(self):\n"
            "        self._cond.notify()  # BAD\n\n"
            "    def put(self, x):",
        )
        f = self._run({"pkg.q": bad})
        assert [(x.rule, x.line) for x in f] == [
            ("condvar-discipline", _line_of(bad, "# BAD")),
        ]
        assert "owning lock" in f[0].message

    def test_condvar_discipline_twin_is_clean(self):
        assert self._run({"pkg.q": _CONDVAR_SRC}) == []

    def test_started_nonjoined_thread_fires(self):
        f = self._run({"pkg.w": _THREAD_SRC})
        assert [(x.rule, x.line) for x in f] == [
            ("thread-lifecycle", _line_of(_THREAD_SRC, "self._t.start()")),
        ]
        assert "non-daemon" in f[0].message

    def test_daemon_and_joined_twins_are_clean(self):
        daemon = _THREAD_SRC.replace(
            "threading.Thread(target=self._run)",
            "threading.Thread(target=self._run, daemon=True)",
        )
        joined = _THREAD_SRC.replace(
            "    def _run(self):",
            "    def stop(self):\n"
            "        self._t.join()\n\n"
            "    def _run(self):",
        )
        assert self._run({"pkg.w": daemon}) == []
        assert self._run({"pkg.w": joined}) == []

    def test_blocking_call_under_lock_fires(self):
        src = textwrap.dedent(
            """
            import threading
            import time

            class Sleeper:
                def __init__(self):
                    self._lock = threading.Lock()

                def nap(self):
                    with self._lock:
                        time.sleep(1)
            """
        )
        f = self._run({"pkg.s": src})
        assert [(x.rule, x.line) for x in f] == [
            ("thread-lifecycle", _line_of(src, "time.sleep(1)")),
        ]
        assert "holding `_lock`" in f[0].message

    def test_two_module_lock_order_cycle(self):
        srcs = {"pkg.a": _CYCLE_A, "pkg.b": _CYCLE_B}
        f = self._run(srcs)
        assert [x.rule for x in f] == ["lock-order-cycle"]
        assert f[0].path == "pkg/a.py"
        assert f[0].line == _line_of(_CYCLE_A, "OTHER.poke()")
        assert f[0].chain == ("pkg.a:A.cross", "pkg.b:B.cross")
        # both halves of the inversion are named with their sites
        assert "pkg/a.py:" in f[0].message and "pkg/b.py:" in f[0].message
        assert "pkg.a:A._lock -> pkg.b:B._lock -> pkg.a:A._lock" \
            in f[0].message

    def test_consistent_order_twin_is_clean(self):
        b_ok = _CYCLE_B.replace(
            "    def cross(self):\n"
            "        with self._lock:\n"
            "            ROOT.poke()",
            "    def cross(self):\n"
            "        ROOT.poke()",
        )
        assert self._run({"pkg.a": _CYCLE_A, "pkg.b": b_ok}) == []

    def test_cycle_needs_class_model_singleton_typing(self):
        """the cycle's inter-module edges exist only through the class
        model's module-global instance typing — the pre-class-model call
        graph never records them."""
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources(
            {"pkg.a": _CYCLE_A, "pkg.b": _CYCLE_B}, type_informed=True)
        assert db.typed_edges == {
            ("pkg.a:cross", "pkg.b:poke"),
            ("pkg.b:cross", "pkg.a:poke"),
        }
        db0 = ProgramDB.from_sources(
            {"pkg.a": _CYCLE_A, "pkg.b": _CYCLE_B}, type_informed=False)
        assert db0.typed_edges == set()


class TestTypeInformedOnTree:
    """Acceptance pins for type-informed resolution on the real tree."""

    def _db(self, **kw):
        import os

        import stmgcn_tpu
        from stmgcn_tpu.analysis.program_db import ProgramDB

        root = os.path.dirname(os.path.abspath(stmgcn_tpu.__file__))
        return ProgramDB.from_root(root, package="stmgcn_tpu", **kw)

    def test_resolves_previously_unresolved_edges(self):
        db = self._db(type_informed=True)
        assert len(db.typed_edges) >= 10
        # every typed edge is NEW information: absent from the untyped
        # graph by construction, and lands on a real known function
        for caller, callee in db.typed_edges:
            assert callee in db.edges
        # the singleton-typed edge the jit-reachability pass gains:
        # jaxmon's REGISTRY.counter(...) through the module-global's
        # inferred MetricsRegistry type
        assert ("stmgcn_tpu.obs.jaxmon:_refresh_recompiles",
                "stmgcn_tpu.obs.registry:counter") in db.typed_edges

    def test_zero_new_findings_on_tree(self):
        from stmgcn_tpu.analysis.concurrency_check import check_concurrency

        typed = check_concurrency(self._db(type_informed=True))
        untyped = check_concurrency(self._db(type_informed=False))
        assert typed == []  # the tree is clean under the deeper graph
        assert untyped == []

    def test_tree_class_model_sees_serving_sync_fields(self):
        db = self._db(type_informed=True)
        mb = db.classes["stmgcn_tpu.serving.microbatch:MicroBatcher"]
        assert "_lock" in mb.locks
        assert mb.condvars.get("_cond") == "_lock"
        assert "_worker" in mb.threads


class TestConcurrencySuppression:
    """Cross-method findings suppress at the *reported* access line
    (satellite f); --include-suppressed lists, never counts."""

    def _suppressed_src(self):
        return _UNGUARDED_SRC.replace(
            "return self._n",
            "return self._n  # stmgcn: ignore[unguarded-attr]",
        )

    def test_suppress_at_reported_line(self):
        from stmgcn_tpu.analysis.concurrency_check import check_concurrency
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({"pkg.box": self._suppressed_src()})
        assert check_concurrency(db) == []

    def test_suppress_at_write_site_does_not_apply(self):
        """the guard evidence line is not the finding line — suppression
        there must NOT silence the read-side finding."""
        from stmgcn_tpu.analysis.concurrency_check import check_concurrency
        from stmgcn_tpu.analysis.program_db import ProgramDB

        src = _UNGUARDED_SRC.replace(
            "self._n += 1",
            "self._n += 1  # stmgcn: ignore[unguarded-attr]",
        )
        db = ProgramDB.from_sources({"pkg.box": src})
        assert [f.rule for f in check_concurrency(db)] == ["unguarded-attr"]

    def test_include_suppressed_lists_but_never_counts(self):
        from stmgcn_tpu.analysis.concurrency_check import check_concurrency
        from stmgcn_tpu.analysis.program_db import ProgramDB

        db = ProgramDB.from_sources({"pkg.box": self._suppressed_src()})
        f = check_concurrency(db, include_suppressed=True)
        assert [x.rule for x in f] == ["unguarded-attr"]
        assert f[0].suppressed is True
        assert f[0].chain == ("pkg.box:Box.bump", "pkg.box:Box.read")
        payload = json.loads(render_json(f))
        assert payload["errors"] == 0 and payload["warnings"] == 0
        assert payload["findings"][0]["suppressed"] is True


@pytest.mark.slow
class TestLintWallTime:
    """The whole-program pass stays fast enough to gate every commit:
    one full ``stmgcn lint`` (AST + class model + concurrency +
    contracts — including the spmd pass's eight real program lowerings
    on the 8-virtual-device mesh) under a wall-time budget with
    headroom (measured ~24s on the dev box with spmd on, ~7s before)."""

    BUDGET_S = 60.0

    def test_full_lint_under_budget(self):
        import os
        import subprocess
        import sys
        import time as _time

        t0 = _time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "stmgcn_tpu.cli", "lint",
             "--format", "json"],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        elapsed = _time.monotonic() - t0
        assert proc.returncode == 0, proc.stderr[-2000:]
        payload = json.loads(proc.stdout)
        assert payload["errors"] == 0 and payload["warnings"] == 0
        assert elapsed < self.BUDGET_S, f"lint took {elapsed:.1f}s"


class TestBranchBandwidthFloor:
    """Satellite b: a-priori floors for the data-dependent branches."""

    def test_nnz_and_floor_math(self):
        from stmgcn_tpu.analysis.collective_check import (
            branch_bandwidth_floor,
            expected_branch_nnz,
        )

        n = 2500
        assert expected_branch_nnz("transport", n) == 20 * n
        assert expected_branch_nnz("similarity", n) == n * n // 10
        # similarity: 250 nnz/row -> floor ceil(249/2) = 125
        assert branch_bandwidth_floor(n, expected_branch_nnz("similarity", n)) == 125
        assert branch_bandwidth_floor(n, expected_branch_nnz("transport", n)) == 10
        assert branch_bandwidth_floor(100, 100) == 0  # diagonal
        with pytest.raises(ValueError):
            expected_branch_nnz("grid", n)

    def _banded_scaled(self, halo):
        from stmgcn_tpu.config import preset

        cfg = preset("scaled")  # 50x50 grid: n=2500
        cfg.mesh.region_strategy = "banded"
        cfg.model.kernel_type = "localpool"  # grid bw 50: out of the way
        cfg.mesh.halo = halo
        return cfg

    def test_boundary_exactly_at_the_similarity_floor(self):
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts

        assert check_collective_contracts(
            [("b", self._banded_scaled(125))]) == []
        f = check_collective_contracts([("b", self._banded_scaled(124))])
        assert [x.rule for x in f] == ["collective-shape"]
        assert "similarity branch's bandwidth floor 125" in f[0].message

    def test_auto_strategy_stays_silent(self):
        """'auto' reroutes dense branches at decomposition time — the
        floor only gates *forced* banded."""
        from stmgcn_tpu.analysis.collective_check import check_collective_contracts
        from stmgcn_tpu.config import preset

        cfg = preset("scaled")
        cfg.mesh.halo = 10  # far below both floors
        assert cfg.mesh.region_strategy == "auto"
        assert check_collective_contracts([("b", cfg)]) == []


@pytest.mark.slow
class TestLintGateScript:
    """scripts/lint_gate.sh stdout contract: exactly one JSON line."""

    def test_stdout_is_one_passing_json_line(self):
        import os
        import subprocess

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            ["bash", os.path.join(repo, "scripts", "lint_gate.sh")],
            capture_output=True, text=True, timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = proc.stdout.splitlines()
        assert len(lines) == 1, proc.stdout
        payload = json.loads(lines[0])
        assert payload["gate"] == "PASS"
        assert payload["lint"] == {
            "exit": 0, "errors": 0, "warnings": 0, "version": 3,
        }
        # concurrency evidence: the pass ran over a real class model,
        # gained typed edges, and found nothing unsuppressed
        assert payload["concurrency"]["exit"] == 0
        assert payload["concurrency"]["findings"] == 0
        assert payload["concurrency"]["classes"] > 0
        assert payload["concurrency"]["typed_edges"] > 0
        assert set(payload["ruff"]) == {"available", "exit"}
        # the traced smoke run: compiled fine, traced spans, and — the
        # dynamic recompile gate — NOTHING compiled after warmup
        assert payload["obs"]["exit"] == 0
        assert payload["obs"]["recompiles_after_warmup"] == 0
        assert payload["obs"]["trace_spans"] > 0
        # the numeric-health section: the health-instrumented smoke
        # train produced records with zero nonfinite counts, and every
        # preset passed the health-overhead config contract
        assert payload["health"]["exit"] == 0
        assert payload["health"]["nonfinite"] == 0
        assert payload["health"]["records"] > 0
        assert payload["health"]["findings"] == 0
        # the closed-loop continual drill: one clean promotion, one
        # poisoned rejection, zero nonfinite in the clean health stream
        assert payload["continual"] == {
            "exit": 0, "promotions": 1, "rejections": 1, "nonfinite": 0,
        }
        # the federation kill-and-recover drill: no hung caller, no
        # cross-generation response, the scheduled kill fired, every
        # city serveable again afterwards, presets pass the topology
        # contract
        assert payload["federation"]["exit"] == 0
        assert payload["federation"]["hung"] == 0
        assert payload["federation"]["cross_generation"] == 0
        assert payload["federation"]["kills"] == 1
        assert payload["federation"]["recovered"] == \
            payload["federation"]["cities"]
        assert payload["federation"]["cities"] > 0
        assert payload["federation"]["findings"] == 0
        # the spmd contract section: every composed program lowered on
        # the virtual mesh, collectives observed, zero manifest/wire/
        # footprint findings
        assert payload["spmd"]["exit"] == 0
        assert payload["spmd"]["programs"] > 0
        assert payload["spmd"]["collectives"] > 0
        assert payload["spmd"]["findings"] == 0
        # the spmd execution smoke: the composed superstep RAN on the
        # 8-virtual-device substrate as the fused mesh program,
        # bit-identical to its single-device twin, zero recompiles
        # after its warmup epoch
        assert payload["spmd_exec"] == {
            "exit": 0, "program": "series_superstep", "n_devices": 8,
            "parity_drift": 0.0, "recompiles_after_warmup": 0,
        }
        # the precision dataflow section: every registered contract
        # program dtype-walked — including the bf16 mixed-precision
        # twins — sites classified, zero policy findings
        assert payload["precision"]["exit"] == 0
        assert payload["precision"]["programs"] > 0
        assert payload["precision"]["bf16_programs"] > 0
        assert payload["precision"]["sites"] > 0
        assert payload["precision"]["findings"] == 0
