"""Inference API tests: checkpoint -> Forecaster -> raw-unit predictions."""

import numpy as np
import pytest

from stmgcn_tpu.config import preset
from stmgcn_tpu.experiment import build_dataset, build_supports, build_trainer
from stmgcn_tpu.inference import Forecaster


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    out = tmp_path_factory.mktemp("ckpt")
    cfg = preset("smoke")
    cfg.data.n_timesteps = 24 * 7 * 2 + 48
    cfg.train.epochs = 1
    cfg.train.batch_size = 16
    cfg.train.out_dir = str(out)
    trainer = build_trainer(cfg, verbose=False)
    trainer.train()
    return cfg, trainer


class TestForecaster:
    def test_matches_trainer_eval(self, trained):
        cfg, trainer = trained
        fc = Forecaster.from_checkpoint(trainer.best_path)
        assert fc.seq_len == cfg.data.seq_len and fc.horizon == 1

        dataset = trainer.dataset
        x, _ = dataset.arrays("test")
        supports = build_supports(cfg, dataset)
        # Forecaster path: raw-unit history in, raw-unit forecast out
        raw_history = dataset.denormalize(x[:8])
        got = fc.predict(supports, raw_history)

        # trainer path: normalized eval + explicit denormalize
        import jax.numpy as jnp

        _, pred = trainer.step_fns.eval_step(
            trainer.params, trainer.supports, jnp.asarray(x[:8]),
            jnp.zeros((8,) + dataset.arrays("test")[1].shape[1:], jnp.float32),
            jnp.ones(8),
        )
        want = dataset.denormalize(np.asarray(pred))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)

    def test_normalized_input_path(self, trained):
        cfg, trainer = trained
        fc = Forecaster.from_checkpoint(trainer.best_path)
        dataset = trainer.dataset
        x, _ = dataset.arrays("validate")
        supports = build_supports(cfg, dataset)
        a = fc.predict(supports, dataset.denormalize(x[:4]))
        b = fc.predict(supports, x[:4], normalized=True)
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-3)

    def test_shape_validation(self, trained):
        cfg, trainer = trained
        fc = Forecaster.from_checkpoint(trainer.best_path)
        with pytest.raises(ValueError, match="history"):
            fc.predict(None, np.zeros((2, 99, 4, 1)))

    def test_rejects_foreign_checkpoint(self, tmp_path, trained):
        _, trainer = trained
        from stmgcn_tpu.train import save_checkpoint

        path = str(tmp_path / "bare.ckpt")
        save_checkpoint(path, trainer.params, trainer.opt_state, {"epoch": 1})
        with pytest.raises(ValueError, match="metadata"):
            Forecaster.from_checkpoint(path)
