"""The checkify sanitizer (SURVEY.md §5.b's in-jit analogue): clean runs
are numerically untouched; a poisoned input fails AT the step with a NaN
diagnostic instead of silently corrupting training state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.data import DemandDataset, WindowSpec, synthetic_dataset
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.train import make_optimizer, make_step_fns


@pytest.fixture(scope="module")
def setup():
    data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2 + 40, seed=0)
    ds = DemandDataset(data, WindowSpec(3, 1, 1, 24))
    supports = jnp.asarray(
        SupportConfig("chebyshev", 1).build_all(ds.adjs.values())
    )
    model = STMGCN(
        m_graphs=3, n_supports=2, seq_len=5, input_dim=ds.n_feats,
        lstm_hidden_dim=8, lstm_num_layers=1, gcn_hidden_dim=8,
    )
    batch = next(ds.batches("train", 4, pad_last=True))
    x, y = jnp.asarray(batch.x), jnp.asarray(batch.y)
    mask = jnp.ones(4, jnp.float32)
    return model, supports, x, y, mask


@pytest.mark.slow
def test_checked_step_matches_unchecked(setup):
    model, supports, x, y, mask = setup
    plain = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse")
    checked = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse", checks="nan")
    p0, o0 = plain.init(jax.random.key(0), supports, x)
    p1, o1 = checked.init(jax.random.key(0), supports, x)
    _, _, l0 = plain.train_step(p0, o0, supports, x, y, mask)
    _, _, l1 = checked.train_step(p1, o1, supports, x, y, mask)
    assert float(l0) == pytest.approx(float(l1), rel=1e-6)


@pytest.mark.slow
def test_checked_step_traps_nan(setup):
    model, supports, x, y, mask = setup
    checked = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse", checks="nan")
    params, opt = checked.init(jax.random.key(0), supports, x)
    bad_x = x.at[0, 0, 0, 0].set(jnp.nan)
    with pytest.raises(Exception, match="nan"):
        out = checked.train_step(params, opt, supports, bad_x, y, mask)
        jax.block_until_ready(out)


def test_checked_eval_traps_and_clean_passes(setup):
    model, supports, x, y, mask = setup
    checked = make_step_fns(model, make_optimizer(2e-3, 1e-4), "mse", checks="float")
    params, _ = checked.init(jax.random.key(0), supports, x)
    loss, _ = checked.eval_step(params, supports, x, y, mask)
    assert np.isfinite(float(loss))
    with pytest.raises(Exception, match="nan"):
        out = checked.eval_step(params, supports, x.at[0].set(jnp.nan), y, mask)
        jax.block_until_ready(out)


def test_invalid_checks_name_rejected(setup):
    model, *_ = setup
    with pytest.raises(ValueError, match="checks must be one of"):
        make_step_fns(model, make_optimizer(2e-3, 0.0), "mse", checks="everything")
