"""Fleet shape-class fast path: planner, trainer parity, serving parity.

The fleet path groups heterogeneous cities into node-count rungs
(``data/fleet.py``) so ONE fused window-free superstep per class covers
every member city in training, and one engine with (city -> class)
routing serves the whole fleet from a single checkpoint
(``serving/fleet.py``). Because padding is provably inert — zero support
rows/cols, gate pooling over a traced real-node count, ``(B, N)`` loss
masks — parity against the materialized per-city oracle is exact
equality, not allclose: per-batch losses, histories, params, opt-state,
and served predictions must match bit for bit across >= 2 classes,
shuffle on/off, padded member cities, a mid-epoch SIGTERM resume, and
cross-city coalesced serving dispatches.
"""

import threading

import jax
import numpy as np
import pytest

from stmgcn_tpu.config import ServingConfig, preset
from stmgcn_tpu.data import (
    HeteroCityDataset,
    MinMaxNormalizer,
    WindowSpec,
    synthetic_dataset,
)
from stmgcn_tpu.data.fleet import FleetPlan, ShapeClass, plan_shape_classes
from stmgcn_tpu.experiment import build_model
from stmgcn_tpu.inference import Forecaster
from stmgcn_tpu.models import STMGCN
from stmgcn_tpu.ops import SupportConfig
from stmgcn_tpu.resilience import FaultPlan, FaultSpec, Preempted
from stmgcn_tpu.serving import FleetServingEngine
from stmgcn_tpu.train import CitySupports, Trainer

BATCH = 8
#: three cities, two shape classes at the default waste budget: N=9 and
#: N=8 share the 9-rung (city 1 carries one padded node row), N=4 is too
#: small for it (waste 5/9 > 0.5) and opens its own rung
CITY_DIMS = ((3, 3), (2, 4), (2, 2))


def city_datas():
    return [
        synthetic_dataset(rows=r, cols=c, n_timesteps=24 * 7 * 2 + 12 * i,
                          seed=i + 1)
        for i, (r, c) in enumerate(CITY_DIMS)
    ]


def build_fleet(out_dir, *, superstep=1, window_free=None, fleet=None,
                shuffle=False, epochs=2, **kw):
    datas = city_datas()
    dataset = HeteroCityDataset(datas, WindowSpec(3, 1, 1, 24))
    sup = CitySupports(
        SupportConfig("chebyshev", 2).build_all(d.adjs.values())
        for d in datas
    )
    model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                   horizon=1, lstm_hidden_dim=8, lstm_num_layers=1,
                   gcn_hidden_dim=8)
    return Trainer(model, dataset, sup, n_epochs=epochs, batch_size=BATCH,
                   shuffle=shuffle, steps_per_superstep=superstep,
                   window_free=window_free, fleet=fleet,
                   out_dir=str(out_dir), verbose=False, **kw)


def same(a, b):
    jax.tree.map(np.testing.assert_array_equal, a, b)


class TestPlanner:
    """plan_shape_classes unit contracts: greedy rung opening, the
    covering rule's waste boundary, and knob validation."""

    def test_two_class_plan(self):
        plan = plan_shape_classes([9, 8, 4])
        assert [(c.n_nodes, c.cities) for c in plan.classes] == [
            (4, (2,)), (9, (0, 1))]
        assert plan.unassigned == ()
        assert plan.class_of == {2: 0, 0: 1, 1: 1}
        assert plan.slot_of == {2: 0, 0: 0, 1: 1}
        assert plan.pad_for(1) == 1 and plan.pad_for(0) == 0

    def test_first_rung_covers_the_largest_city(self):
        plan = plan_shape_classes([10, 9], max_classes=1, max_pad_waste=0.0)
        assert [(c.n_nodes, c.cities) for c in plan.classes] == [(10, (0,))]
        assert plan.unassigned == (1,)
        assert plan.pad_for(1) is None

    def test_waste_boundary_exact(self):
        """Membership rule is rung - n > waste * rung: equality joins,
        one epsilon below drops to unassigned."""
        at = plan_shape_classes([144, 100], max_classes=1,
                                max_pad_waste=44 / 144)
        assert at.classes[0].cities == (0, 1) and at.unassigned == ()
        below = plan_shape_classes([144, 100], max_classes=1,
                                   max_pad_waste=44 / 144 - 1e-9)
        assert below.classes[0].cities == (0,) and below.unassigned == (1,)

    def test_node_multiple_rounds_rungs_up(self):
        plan = plan_shape_classes([10], node_multiple=8)
        assert plan.classes[0].n_nodes == 16
        assert plan.classes[0].pad_for(0) == 6

    def test_waste_properties(self):
        cls = ShapeClass(n_nodes=10, cities=(0, 1), city_n_nodes=(10, 8),
                         nnz=100, city_nnz=(100, 64))
        assert cls.node_waste == pytest.approx(0.2)
        assert cls.nnz_waste == pytest.approx(0.36)
        plan = FleetPlan(classes=(cls,), unassigned=())
        assert plan.node_waste == pytest.approx(0.2)

    @pytest.mark.parametrize("kwargs,match", [
        (dict(max_classes=0), "max_classes"),
        (dict(max_pad_waste=1.0), "max_pad_waste"),
        (dict(max_pad_waste=-0.1), "max_pad_waste"),
    ])
    def test_knob_validation(self, kwargs, match):
        with pytest.raises(ValueError, match=match):
            plan_shape_classes([4, 9], **kwargs)

    def test_bad_sizes_and_ragged_nnz(self):
        with pytest.raises(ValueError, match="positive"):
            plan_shape_classes([4, 0])
        with pytest.raises(ValueError, match="align"):
            plan_shape_classes([4, 9], city_nnz=[16])


class TestFleetTrainerParity:
    """The fleet fused path vs its two oracles, bit for bit: the
    materialized per-city loop at the same class shapes (fleet=True,
    S=1, window_free=False) and the per-step window-free run."""

    @pytest.mark.parametrize("shuffle", [False, True])
    def test_bit_identical_to_oracles(self, tmp_path, shuffle):
        fast = build_fleet(tmp_path / "fast", superstep=3, shuffle=shuffle)
        assert fast.train_path == "fleet_superstep"
        assert fast.fallback_reason is None
        assert [(c.n_nodes, c.cities) for c in fast._fleet_plan.classes] == [
            (4, (2,)), (9, (0, 1))]
        assert fast._node_pads == (0, 1, 0)  # padded member city mid-fleet
        hist_fast = fast.train()

        oracle = build_fleet(tmp_path / "mat", superstep=1, fleet=True,
                             window_free=False, shuffle=shuffle)
        assert oracle.train_path == "per_step" and not oracle._window_free
        assert oracle._node_pads == fast._node_pads
        hist_mat = oracle.train()

        wf1 = build_fleet(tmp_path / "wf1", superstep=1, fleet=True,
                          window_free=True, shuffle=shuffle)
        hist_wf = wf1.train()

        same(hist_fast, hist_mat)
        same(hist_fast, hist_wf)
        same(fast.params, oracle.params)
        same(jax.tree.leaves(fast.opt_state), jax.tree.leaves(oracle.opt_state))
        same(fast.params, wf1.params)

    def test_unassigned_city_falls_back_per_step_bit_exact(self, tmp_path):
        """A 1-class budget with a tight waste threshold leaves the small
        cities unassigned: they run the per-step loop while city 0 stays
        fused — and the mixed run still matches the oracle bitwise."""
        knobs = dict(fleet_max_classes=1, fleet_max_pad_waste=0.05)
        fast = build_fleet(tmp_path / "fast", superstep=3, **knobs)
        assert fast.train_path == "fleet_superstep"
        assert "no-class-fit" in fast.fallback_reason
        assert "[1, 2]" in fast.fallback_reason
        assert sorted(fast._fleet_plan.unassigned) == [1, 2]
        assert sorted(fast._fleet_cities) == [0]
        hist_fast = fast.train()

        oracle = build_fleet(tmp_path / "mat", superstep=1, fleet=True,
                             window_free=False, **knobs)
        hist_mat = oracle.train()
        same(hist_fast, hist_mat)
        same(fast.params, oracle.params)


class TestFleetPaths:
    """train_path / fallback_reason surfacing and fleet=True blockers."""

    def test_fleet_false_keeps_materialized_loop(self, tmp_path):
        t = build_fleet(tmp_path, superstep=3, fleet=False)
        assert t.train_path == "per_step"
        assert "fleet=False" in t.fallback_reason

    def test_hetero_window_free_false_is_the_oracle_path(self, tmp_path):
        t = build_fleet(tmp_path, superstep=3, window_free=False)
        assert t.train_path == "per_step"
        assert "window_free=False" in t.fallback_reason

    def test_fleet_true_on_homogeneous_raises(self, tmp_path):
        from stmgcn_tpu.data import DemandDataset

        data = synthetic_dataset(rows=3, n_timesteps=24 * 7 * 2, seed=1)
        dataset = DemandDataset(data, WindowSpec(3, 1, 1, 24))
        sup = SupportConfig("chebyshev", 2).build_all(dataset.adjs.values())
        model = STMGCN(m_graphs=3, n_supports=3, seq_len=5, input_dim=1,
                       horizon=1, lstm_hidden_dim=8, lstm_num_layers=1,
                       gcn_hidden_dim=8)
        with pytest.raises(ValueError, match="homogeneous"):
            Trainer(model, dataset, sup, n_epochs=1, batch_size=BATCH,
                    fleet=True, out_dir=str(tmp_path), verbose=False)

    def test_fleet_true_on_streamed_data_raises(self, tmp_path):
        with pytest.raises(ValueError, match="resident"):
            build_fleet(tmp_path, superstep=3, fleet=True,
                        data_placement="stream")

    def test_trainer_validates_knobs(self, tmp_path):
        with pytest.raises(ValueError, match="fleet_max_classes"):
            build_fleet(tmp_path, fleet_max_classes=0)
        with pytest.raises(ValueError, match="fleet_max_pad_waste"):
            build_fleet(tmp_path, fleet_max_pad_waste=1.0)


class TestFleetResume:
    """Mid-epoch SIGTERM on the fleet path: resume must end bit-identical
    to the uninterrupted fleet run (same drill as
    test_window_free.TestWindowFreeResume, on the per-class path)."""

    def test_sigterm_resume_bit_exact(self, tmp_path):
        ref = build_fleet(tmp_path / "ref", superstep=3)
        ref_hist = ref.train()

        plan = FaultPlan(FaultSpec("sigterm", epoch=2, step=4))
        faulted = build_fleet(tmp_path / "run", superstep=3, fault_plan=plan)
        assert faulted.train_path == "fleet_superstep"
        with pytest.raises(Preempted, match="--resume auto"):
            faulted.train()

        resumed = build_fleet(tmp_path / "run", superstep=3)
        meta = resumed.restore_auto()
        assert meta is not None
        assert meta["epoch"] == 2 and meta["batch_in_epoch"] > 0
        hist = resumed.train()

        same(ref.params, resumed.params)
        same(jax.tree.leaves(ref.opt_state), jax.tree.leaves(resumed.opt_state))
        assert hist["train"][-1] == ref_hist["train"][-1]
        assert hist["validate"][-1] == ref_hist["validate"][-1]


LADDER = ServingConfig(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0)


@pytest.fixture(scope="module")
def fleet_setup():
    """A train-free heterogeneous Forecaster (freshly-initialized params
    + per-city fitted normalizers) — the same recipe as
    tests/test_serving.py, lifted to three cities of two shape classes."""
    cfg = preset("smoke")
    datas = city_datas()
    n_nodes = [d.demand.shape[1] for d in datas]
    sups = [
        np.asarray(
            SupportConfig(cfg.model.kernel_type, cfg.model.K).build_all(
                d.adjs.values()
            ),
            np.float32,
        )[: cfg.model.m_graphs]
        for d in datas
    ]
    model = build_model(cfg, 1)
    import jax.numpy as jnp

    x = jnp.zeros((2, cfg.data.seq_len, n_nodes[0], 1), jnp.float32)
    params = model.init(jax.random.key(0), jnp.asarray(sups[0]), x)
    normalizers = [MinMaxNormalizer.fit(np.asarray(d.demand)) for d in datas]
    fc = Forecaster(
        model, params, None, cfg,
        {"input_dim": 1, "n_nodes": n_nodes}, normalizers,
    )
    return fc, sups, n_nodes


@pytest.fixture(scope="module")
def fleet_engine(fleet_setup):
    fc, sups, _ = fleet_setup
    eng = fc.fleet_engine(sups, config=LADDER)
    yield eng
    eng.close()


class TestFleetServing:
    """One engine, three cities, two classes: bit-parity against the
    per-city Forecaster and the coalescing the per-city engine can't do."""

    def test_routing_and_buckets(self, fleet_engine):
        eng = fleet_engine
        assert eng.n_cities == 3
        assert eng.buckets == (1, 2, 4)
        assert eng.class_of(0) == eng.class_of(1) != eng.class_of(2)

    @pytest.mark.parametrize("city", [0, 1, 2])
    def test_bit_identical_to_forecaster(self, fleet_setup, fleet_engine,
                                         city):
        fc, sups, n_nodes = fleet_setup
        rng = np.random.default_rng(city)
        h = rng.gamma(2.0, 20.0,
                      size=(3, fc.seq_len, n_nodes[city], 1)).astype(np.float32)
        ref = fc.predict(sups[city], h, city=city)
        np.testing.assert_array_equal(ref, fleet_engine.predict(h, city=city))
        np.testing.assert_array_equal(
            ref, fleet_engine.predict_direct(h, city=city))

    def test_oversized_batch_splits(self, fleet_setup, fleet_engine):
        fc, sups, n_nodes = fleet_setup
        rng = np.random.default_rng(7)
        h = rng.gamma(2.0, 20.0,
                      size=(9, fc.seq_len, n_nodes[0], 1)).astype(np.float32)
        ref = fc.predict(sups[0], h, city=0)
        np.testing.assert_array_equal(ref, fleet_engine.predict(h, city=0))

    def test_cross_city_dispatch_coalesces(self, fleet_setup, fleet_engine):
        """Concurrent requests for the two same-class cities must share
        at least one dispatch — and stay bit-exact doing it."""
        fc, sups, n_nodes = fleet_setup
        eng = fleet_engine
        before = eng.cross_city_dispatches
        rng = np.random.default_rng(11)
        hs = {
            c: rng.gamma(2.0, 20.0,
                         size=(2, fc.seq_len, n_nodes[c], 1)).astype(np.float32)
            for c in (0, 1)
        }
        refs = {c: fc.predict(sups[c], hs[c], city=c) for c in (0, 1)}
        outs = {}
        barrier = threading.Barrier(2)

        def worker(c):
            barrier.wait()
            outs[c] = eng.predict(hs[c], city=c)

        threads = [threading.Thread(target=worker, args=(c,)) for c in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for c in (0, 1):
            np.testing.assert_array_equal(refs[c], outs[c])
        assert eng.cross_city_dispatches > before

    def test_unassigned_city_gets_private_class(self, fleet_setup):
        """A waste budget that strands the small cities still serves
        them (exact-fit private classes), bit-exact."""
        fc, sups, n_nodes = fleet_setup
        with fc.fleet_engine(sups, config=LADDER, max_classes=1,
                             max_pad_waste=0.05) as eng:
            assert eng.plan.unassigned == (1, 2)
            assert eng.class_of(0) != eng.class_of(1) != eng.class_of(2)
            rng = np.random.default_rng(3)
            for c in range(3):
                h = rng.gamma(
                    2.0, 20.0,
                    size=(2, fc.seq_len, n_nodes[c], 1)).astype(np.float32)
                np.testing.assert_array_equal(
                    fc.predict(sups[c], h, city=c), eng.predict(h, city=c))

    def test_validation_errors(self, fleet_setup, fleet_engine):
        fc, sups, n_nodes = fleet_setup
        with pytest.raises(ValueError, match="city"):
            fleet_engine.predict(
                np.zeros((1, fc.seq_len, 9, 1), np.float32), city=9)
        with pytest.raises(ValueError, match="history"):
            fleet_engine.predict(
                np.zeros((1, fc.seq_len, 7, 1), np.float32), city=0)

    def test_homogeneous_checkpoint_rejected(self, fleet_setup):
        fc, sups, n_nodes = fleet_setup
        flat = Forecaster(fc.model, fc.params, fc.normalizers[0], fc.config,
                          {"input_dim": 1, "n_nodes": n_nodes[0]})
        with pytest.raises(ValueError, match="ServingEngine"):
            FleetServingEngine.from_forecaster(flat, [sups[0]])

    def test_support_shape_mismatch_rejected(self, fleet_setup):
        fc, sups, _ = fleet_setup
        with pytest.raises(ValueError, match="support"):
            FleetServingEngine.from_forecaster(fc, sups[:2])
        bad = [sups[0], sups[0], sups[2]]  # city 1 stack at the wrong N
        with pytest.raises(ValueError, match="city 1"):
            FleetServingEngine.from_forecaster(fc, bad)


class TestPlumbing:
    """Config / CLI / experiment wiring for the fleet knobs."""

    def test_cli_round_trip(self):
        from stmgcn_tpu.cli import build_parser, config_from_args

        p = build_parser()
        assert config_from_args(p.parse_args([])).train.fleet is None
        on = config_from_args(p.parse_args(["--fleet"]))
        assert on.train.fleet is True
        off = config_from_args(p.parse_args(["--no-fleet"]))
        assert off.train.fleet is False
        knobs = config_from_args(p.parse_args(
            ["--fleet-max-classes", "3", "--fleet-max-pad-waste", "0.25"]))
        assert knobs.train.fleet_max_classes == 3
        assert knobs.train.fleet_max_pad_waste == 0.25

    def test_build_trainer_engages_fleet(self, tmp_path):
        from stmgcn_tpu.experiment import build_trainer

        cfg = preset("multicity")
        cfg.data.n_cities = 3
        cfg.data.city_rows = (3, 3, 2)
        cfg.data.city_timesteps = (24 * 7 * 2, 24 * 7 * 2 + 12, 24 * 7 * 2)
        cfg.data.hetero = True
        cfg.mesh.dp = 1
        cfg.train.steps_per_superstep = 3
        cfg.train.epochs = 1
        cfg.train.out_dir = str(tmp_path)
        t = build_trainer(cfg, verbose=False)
        assert t.train_path == "fleet_superstep"
        assert t._fleet_plan is not None
        assert sorted(t._fleet_cities) == [0, 1, 2]
