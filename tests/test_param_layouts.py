"""Branch-param layout converters: vmapped <-> looped checkpoints."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from stmgcn_tpu.models import STMGCN, to_looped_params, to_vmapped_params

KW = dict(m_graphs=2, n_supports=3, seq_len=5, input_dim=1,
          lstm_hidden_dim=8, lstm_num_layers=2, gcn_hidden_dim=8)


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    sup = jnp.asarray((rng.normal(size=(2, 3, 16, 16)) * 0.2).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(4, 5, 16, 1)).astype(np.float32))
    return sup, x


def test_roundtrip_identity(problem):
    sup, x = problem
    vm = STMGCN(**KW).init(jax.random.key(0), sup, x)
    back = to_vmapped_params(to_looped_params(vm, 2), 2)
    jax.tree.map(np.testing.assert_array_equal, back, vm)


def test_converted_params_produce_identical_forward(problem):
    sup, x = problem
    vmapped_model = STMGCN(**KW)
    looped_model = STMGCN(**KW, vmap_branches=False)

    vm = vmapped_model.init(jax.random.key(0), sup, x)
    want = vmapped_model.apply(vm, sup, x)
    got = looped_model.apply(to_looped_params(vm, 2), sup, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)

    lp = looped_model.init(jax.random.key(1), sup, x)
    want2 = looped_model.apply(lp, sup, x)
    got2 = vmapped_model.apply(to_vmapped_params(lp, 2), sup, x)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), rtol=1e-6)


def test_wrong_layout_raises(problem):
    sup, x = problem
    vm = STMGCN(**KW).init(jax.random.key(0), sup, x)
    lp = STMGCN(**KW, vmap_branches=False).init(jax.random.key(0), sup, x)
    with pytest.raises(ValueError, match="vmapped-layout"):
        to_looped_params(lp, 2)
    with pytest.raises(ValueError, match="looped-layout"):
        to_vmapped_params(vm, 2)
    with pytest.raises(ValueError, match="branch axis"):
        to_looped_params(vm, 3)
